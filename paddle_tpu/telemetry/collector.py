"""Telemetry collector daemon: fleet-wide time series, alerts, and
cross-process trace timelines from pushed telemetry.

Everything before this module is pull-only and per-process: each
trainer/replica serves its own ``/metrics``, and journal shipping
exists only for fleet-OWNED replicas (``FleetRouter.ship_journals``).
The collector inverts the direction: ANY process — a trainer, an
out-of-process serving replica, a router — runs a background
:class:`~paddle_tpu.telemetry.shipper.Shipper` (auto-started by
``PDTPU_TELEMETRY_ADDR``, or ``ship_to(addr)``) that PUSHES its
journal-ring deltas and periodic registry snapshots here over the
same length-prefixed framed wire the async-PS path speaks
(:class:`~paddle_tpu.parallel.async_ps.FramedClient` reuse).

Wire verbs (shipper → collector; one ASCII header line + one json
body; replies ``OK <n>`` / ``ERR <reason>``)::

    PING
    EVENTS <origin> <len>    + {"run": ..., "events": [...]}
    SNAPSHOT <origin> <len>  + {"t": ..., "families": families_snapshot}
    STATS                    (reply: ``OK {json}`` — ingest/store ctrs)
    SEGMENTS <len>           + {"list": true} | {"fetch": name,
                             "offset": k[, "limit": n]} (framed reply
                             body: listing json / raw segment bytes —
                             the cross-host standby's replication pull)

``EVENTS`` ingestion is idempotent: events are deduplicated by a
per-``(origin, run)`` high-water ``seq``, so a shipper whose reply was
lost simply resends the batch (no at-most-once dance needed on a
telemetry path — double-counting is prevented server-side).

The collector maintains:

- a :class:`SeriesStore` — per-origin bounded time-series rings over
  every pushed metric sample (counters/gauges as ``(t, value)``,
  histograms as ``(t, bucket counts)``), the substrate the
  :class:`~paddle_tpu.telemetry.alerts.AlertEngine` evaluates every
  ``eval_interval`` and an autoscaler can read;
- its OWN :class:`~paddle_tpu.telemetry.journal.RunJournal` holding
  the ingested fleet-wide event stream (every event keeps its origin
  run/seq and gains ``origin=``) — one ring answers "what was the
  whole fleet doing around this span";
- HTTP read endpoints (:meth:`TelemetryCollector.serve_http`):
  ``/metrics`` (every origin's latest snapshot merged under an
  ``origin`` label — naming-contract clean), ``/alerts`` (JSON,
  firing + pending + recently-resolved), and ``/timeline?trace=<span>``
  (the cross-process waterfall of one trace id, assembled from the
  ingested journals; ``&format=text`` renders it).

An alert transition journals ``alert.firing``/``alert.resolved`` and
— for ``page``-severity rules (or all, with ``dump_on_fire=True``) —
triggers a local flight dump carrying the fleet-wide ring, so the
evidence is on disk the moment the pager goes off.

**Durability & HA** (``store_dir=``): every ingest is written through
to a :class:`~paddle_tpu.telemetry.store.SegmentStore` — a segmented,
CRC-framed, retention-bounded (time AND bytes) append-only log. A
restart replays it: rings, dedupe high-water marks, the fleet journal,
and alert firing/pending state all come back (a firing alert stays
firing with its original clock — no re-fire, no resolve flap), and
``GET /query?metric=...&labels=...&from=...&to=...&step=...`` range
reads serve from the log so history survives the process. A SECOND
collector started with ``standby=True`` over the same (shared-
filesystem) ``store_dir`` ingests nothing until the first failed-over
push arrives — the shipper's comma-separated ``PDTPU_TELEMETRY_ADDR``
failover list routes pushes to it once the primary dies — at which
point it PROMOTES by replaying the log. A standby on ANOTHER machine
(no shared filesystem) passes ``replicate_from="host:port"`` instead:
it continuously pulls the primary's sealed segments and open-segment
tail over the ``SEGMENTS`` verb into its OWN ``store_dir``
(CRC-re-verified against each segment's sidecar on receipt), and the
promotion fence moves from heartbeat-file stamps to the replication
stream — a standby refuses to promote while its replication source
still answers a direct probe, so a returning primary keeps the pen. Alert rules hot-reload via
SIGHUP (the daemon re-lints ``--rules``) or ``POST /rules``; findings
from :func:`~paddle_tpu.telemetry.alerts.lint_rules` REJECT the
reload, success journals ``alert.rules_reloaded``.

Run in-process (``TelemetryCollector()``) or standalone::

    python -m paddle_tpu.telemetry.collector [--port N] [--http-port N]
        [--rules rules.json] [--eval-interval S] [--flight-root DIR]

The daemon prints ``PORT <n>`` and ``HTTP <n>`` once listening (the
:class:`CollectorProcess` handshake, same discipline as
``replica_main``).
"""

from __future__ import annotations

import json
import math
import socket
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import alerts as _alerts
from .journal import RunJournal
from .recorder import FlightRecorder
from .registry import (MetricFamily, _series_key, counter_family,
                       families_from_snapshot, gauge_family, merge_exports)


def _log():
    import logging
    return logging.getLogger("paddle_tpu.telemetry.collector")


def _reply_json(conn: socket.socket, payload) -> None:
    """Framed reply body: ``OK <len>\\n`` + payload. A dict/list is
    JSON-encoded; raw bytes pass through (the SEGMENTS fetch form ships
    segment-file bytes verbatim — their integrity rides the CRC
    sidecar, not the frame)."""
    if not isinstance(payload, (bytes, bytearray)):
        payload = json.dumps(payload, sort_keys=True,
                             separators=(",", ":")).encode()
    conn.sendall(b"OK %d\n" % len(payload) + bytes(payload))


# -- per-origin time series ---------------------------------------------------


class SeriesStore:
    """Bounded time-series rings over pushed metric snapshots, keyed by
    series (name + labels, the pushing origin stamped as an ``origin``
    label). Counters/gauges ring ``(t, value)``; histograms ring
    ``(t, bucket counts, sum, count)`` so windowed quantiles come from
    bucket DELTAS. Origins that stop pushing for ``origin_expiry_s``
    are retired wholesale (their series and last-push mark dropped) —
    which is what lets a replica-down absence alert RESOLVE once the
    operator replaced the process."""

    def __init__(self, max_points: int = 512, origin_expiry_s: float = 60.0,
                 value_ttl_s: float = 60.0):
        self.max_points = int(max_points)
        self.origin_expiry_s = float(origin_expiry_s)
        # a sample older than this yields NO threshold verdict (and a
        # rate window with no sample inside it yields none either): a
        # dead origin's last breaker_open=1 gauge must not keep paging
        # until origin expiry — staleness is the absence alert's job
        self.value_ttl_s = float(value_ttl_s)
        self._lock = threading.Lock()
        # series key -> ring; meta: key -> (name, labels, type[, bounds])
        self._rings: Dict[str, deque] = {}
        self._meta: Dict[str, Tuple[str, Dict[str, str], str, Any]] = {}
        self._by_origin: Dict[str, set] = {}
        # metric name -> series keys: rule matching must not scan every
        # stored series under the lock on every eval tick
        self._by_name: Dict[str, set] = {}
        self._latest_snap: Dict[str, Dict[str, Any]] = {}
        self.last_push: Dict[str, float] = {}

    # -- writes --------------------------------------------------------------

    @staticmethod
    def _sanitize(snapshot) -> Dict[str, Any]:
        """Coerce a PUSHED snapshot into the strict families_snapshot
        shape BEFORE storing it: a version-skewed or buggy client must
        not be able to poison every later ``/metrics`` read (a family
        missing ``help`` becomes a visible ``validate_families``
        violation, never a 500 on scrape). VALUES are validated too —
        a scalar sample must be float-coercible and a histogram sample
        a well-formed bounds/counts dict, or the SAMPLE is dropped:
        one bad process must never make the fleet-wide scrape
        unrenderable."""
        out: Dict[str, Any] = {}
        for name, fam in (snapshot or {}).items():
            if not isinstance(fam, dict):
                continue
            ftype = str(fam.get("type", "untyped"))
            samples = []
            for s in fam.get("samples") or []:
                if not isinstance(s, dict) or "value" not in s:
                    continue
                value = s["value"]
                if ftype == "histogram":
                    if not isinstance(value, dict):
                        continue
                    try:
                        bounds = [float(b) for b in
                                  value.get("bounds") or []]
                        counts = [int(c) for c in
                                  value.get("counts") or []]
                        value = {"bounds": bounds, "counts": counts,
                                 "sum": float(value.get("sum", 0.0)),
                                 "count": int(value.get("count", 0))}
                    except (TypeError, ValueError):
                        continue
                    if len(counts) != len(bounds) + 1:
                        continue
                else:
                    try:
                        value = float(value)
                    except (TypeError, ValueError):
                        continue
                labels = s.get("labels")
                samples.append(
                    {"labels": ({str(k): str(v)
                                 for k, v in labels.items()}
                                if isinstance(labels, dict) else {}),
                     "value": value})
            out[str(name)] = {"type": ftype,
                              "help": str(fam.get("help", "")),
                              "samples": samples}
        return out

    def ingest(self, origin: str, snapshot: Dict[str, Any],
               t: Optional[float] = None, sanitized: bool = False) -> int:
        """Absorb one origin's ``families_snapshot`` dict (sanitized —
        see :meth:`_sanitize`; ``sanitized=True`` skips the pass for a
        snapshot that already went through it, e.g. a segment-log
        replay of a previously-sanitized push); returns the number of
        samples ringed."""
        t = time.time() if t is None else t
        if not sanitized:
            snapshot = self._sanitize(snapshot)
        n = 0
        with self._lock:
            self._latest_snap[origin] = snapshot
            self.last_push[origin] = t
            keys = self._by_origin.setdefault(origin, set())
            for name, fam in snapshot.items():
                ftype = fam.get("type", "untyped")
                for s in fam.get("samples", []):
                    labels = dict(s.get("labels", {}))
                    labels.setdefault("origin", origin)
                    key = _series_key(name, labels)
                    ring = self._rings.get(key)
                    if ring is None:
                        ring = self._rings[key] = deque(
                            maxlen=self.max_points)
                    value = s.get("value")
                    if ftype == "histogram" and isinstance(value, dict):
                        self._meta[key] = (name, labels, ftype,
                                           tuple(value.get("bounds", ())))
                        ring.append((t, tuple(value.get("counts", ())),
                                     float(value.get("sum", 0.0)),
                                     int(value.get("count", 0))))
                    else:
                        try:
                            v = float(value)
                        except (TypeError, ValueError):
                            continue
                        self._meta[key] = (name, labels, ftype, None)
                        ring.append((t, v))
                    keys.add(key)
                    self._by_name.setdefault(name, set()).add(key)
                    n += 1
        return n

    def mark_push(self, origin: str, t: Optional[float] = None) -> None:
        """An EVENTS-only push still proves the origin alive."""
        with self._lock:
            self.last_push[origin] = time.time() if t is None else t
            self._by_origin.setdefault(origin, set())

    def expire(self, now: Optional[float] = None) -> List[str]:
        """Retire origins silent past ``origin_expiry_s``; returns the
        retired names."""
        now = time.time() if now is None else now
        with self._lock:
            stale = [o for o, t in self.last_push.items()
                     if now - t > self.origin_expiry_s]
            for origin in stale:
                self._retire_locked(origin)
        return stale

    def retire(self, origin: str) -> None:
        """Drop one origin wholesale regardless of its push age — the
        segment-log replay path for a persisted ``retire`` record (an
        expiry that already happened must not resurrect on restart)."""
        with self._lock:
            self._retire_locked(origin)

    def _retire_locked(self, origin: str) -> None:
        self.last_push.pop(origin, None)
        self._latest_snap.pop(origin, None)
        for key in self._by_origin.pop(origin, set()):
            self._rings.pop(key, None)
            meta = self._meta.pop(key, None)
            if meta is not None:
                named = self._by_name.get(meta[0])
                if named is not None:
                    named.discard(key)
                    if not named:
                        del self._by_name[meta[0]]

    # -- reads ---------------------------------------------------------------

    def origins(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.last_push)

    def latest_snapshots(self) -> Dict[str, Dict[str, Any]]:
        """Per-origin latest ``families_snapshot`` dicts (copied under
        the store lock) — the raw material of :meth:`latest_families`
        and the collector's merged export."""
        with self._lock:
            return dict(self._latest_snap)

    def latest_families(self) -> List[MetricFamily]:
        """Every origin's latest snapshot, merged under ``origin`` —
        the fleet-wide ``/metrics`` body (same primitive as the fleet
        router's ``replica`` merge, so the naming contract holds)."""
        return merge_exports(
            {origin: families_from_snapshot(snap)
             for origin, snap in self.latest_snapshots().items()},
            label="origin")

    def _match_locked(self, metric: str,
                      labels: Dict[str, str]) -> List[str]:
        out = []
        for key in self._by_name.get(metric, ()):
            slabels = self._meta[key][1]
            if all(slabels.get(k) == v for k, v in labels.items()):
                out.append(key)
        return sorted(out)

    # -- the AlertEngine store interface -------------------------------------

    def latest_values(self, metric: str, labels: Dict[str, str],
                      now: Optional[float] = None
                      ) -> List[Tuple[str, Optional[float]]]:
        """Latest sample per matching series — skipping samples older
        than ``value_ttl_s`` (a dead origin's frozen gauge yields no
        verdict; its silence is the absence alert's signal)."""
        now = time.time() if now is None else now
        with self._lock:
            out = []
            for key in self._match_locked(metric, labels):
                ring = self._rings.get(key)
                if not ring or self._meta[key][2] == "histogram":
                    continue
                t1, v1 = ring[-1][0], ring[-1][1]
                if now - t1 > self.value_ttl_s:
                    continue
                out.append((key, v1))
            return out

    def rates(self, metric: str, labels: Dict[str, str], window_s: float,
              now: float) -> List[Tuple[str, Optional[float]]]:
        """Per-second increase over the window: newest sample vs the
        newest sample at/just before the window start (so a window
        spanning exactly two flushes still rates). A decrease (process
        restart reset the counter) clamps to the post-reset value over
        the window rather than going negative. A series with NO sample
        inside the window yields no verdict — a dead origin's last
        burst must not keep a rate alert firing on wholly-stale data
        (the quantile form's idle-window contract, applied here
        too)."""
        with self._lock:
            out = []
            for key in self._match_locked(metric, labels):
                ring = self._rings.get(key)
                if not ring or self._meta[key][2] == "histogram":
                    continue
                pts = list(ring)
                t1, v1 = pts[-1][0], pts[-1][1]
                if t1 < now - window_s:
                    continue  # every sample predates the window
                base = None
                for t0, v0 in reversed(pts[:-1]):
                    base = (t0, v0)
                    if t0 <= now - window_s:
                        break
                if base is None or base[0] >= t1:
                    continue  # a single sample rates nothing
                dv = v1 - base[1]
                if dv < 0:
                    dv = v1  # counter reset: count from zero
                out.append((key, dv / (t1 - base[0])))
            return out

    def quantiles(self, metric: str, labels: Dict[str, str], q: float,
                  window_s: float, now: float
                  ) -> List[Tuple[str, Optional[float]]]:
        """Histogram quantile from the bucket-count DELTA across the
        window (upper-bound estimate, the ``histogram_quantile``
        discipline); a window with no observations yields no verdict
        (the series is skipped, not compared against stale totals)."""
        with self._lock:
            out = []
            for key in self._match_locked(metric, labels):
                meta = self._meta[key]
                if meta[2] != "histogram":
                    continue
                ring = self._rings.get(key)
                if not ring:
                    continue
                pts = list(ring)
                t1, c1 = pts[-1][0], pts[-1][1]
                if t1 < now - window_s:
                    continue  # every sample predates the window
                base = None
                for p in reversed(pts[:-1]):
                    base = p
                    if p[0] <= now - window_s:
                        break
                if base is None:
                    # a single ringed sample: its counts are ALL-TIME
                    # totals, not a window delta — no verdict (the
                    # contract above), never a spurious cold-start p99
                    continue
                c0 = base[1]
                if len(c0) != len(c1):
                    c0 = (0,) * len(c1)
                delta = [max(0, a - b) for a, b in zip(c1, c0)]
                value = _quantile_from_counts(meta[3] or (), delta, q)
                if value is not None:
                    out.append((key, value))
            return out

    def range_query(self, metric: str,
                    labels: Optional[Dict[str, str]] = None,
                    start: float = 0.0, end: Optional[float] = None,
                    step: float = 0.0) -> Dict[str, Any]:
        """In-memory range read over the bounded rings — the ``/query``
        fallback for a collector WITHOUT persistence (same response
        shape as :meth:`~paddle_tpu.telemetry.store.SegmentStore.query`,
        but the horizon is the ring, not the retention window)."""
        from .store import downsample

        labels = dict(labels or {})
        end = time.time() if end is None else end
        out = []
        with self._lock:
            for key in self._match_locked(metric, labels):
                if self._meta[key][2] == "histogram":
                    continue
                pts = [(t, v) for t, v in self._rings.get(key, ())
                       if start <= t <= end]
                out.append({"key": key, "labels": dict(self._meta[key][1]),
                            "points": [[round(t, 6), v] for t, v in
                                       downsample(pts, start, step)]})
        return {"metric": metric, "matchers": labels, "from": start,
                "to": end, "step": step, "series": out}

    def staleness(self, metric: str, labels: Dict[str, str], now: float
                  ) -> List[Tuple[str, float]]:
        with self._lock:
            out = []
            for key in self._match_locked(metric, labels):
                ring = self._rings.get(key)
                if ring:
                    out.append((key, now - ring[-1][0]))
            return out

    def origin_staleness(self, now: float) -> List[Tuple[str, float]]:
        with self._lock:
            return sorted((origin, now - t)
                          for origin, t in self.last_push.items())


def _quantile_from_counts(bounds, counts, q: float) -> Optional[float]:
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target:
            return float(bounds[i]) if i < len(bounds) else math.inf
    return math.inf


# -- timeline assembly --------------------------------------------------------


def assemble_timeline(events: List[Dict[str, Any]],
                      span: str) -> Dict[str, Any]:
    """The cross-process waterfall of one trace id: every journal
    event carrying ``span``, sorted by wall clock, with per-event
    offsets from the first — the feeder fill → fused dispatch → PS
    wire → serving submit/dispatch/complete lifecycle laid out across
    however many processes shipped it."""
    rows = sorted((e for e in events if e.get("span") == span),
                  key=lambda e: (e.get("t", 0.0), e.get("seq", 0)))
    if not rows:
        return {"span": span, "events": [], "origins": [],
                "duration_s": 0.0}
    t0 = rows[0].get("t", 0.0)
    out_rows = []
    for e in rows:
        out_rows.append({
            "t": e.get("t"),
            "offset_s": round(float(e.get("t", t0)) - t0, 6),
            "origin": e.get("origin", "local"),
            "run": e.get("run"),
            "seq": e.get("seq"),
            "kind": e.get("kind"),
            "detail": {k: v for k, v in e.items()
                       if k not in ("t", "origin", "run", "seq", "kind",
                                    "span")},
        })
    origins = sorted({r["origin"] for r in out_rows})
    return {"span": span,
            "t0": t0,
            "duration_s": round(rows[-1].get("t", t0) - t0, 6),
            "origins": origins,
            "events": out_rows}


def render_timeline_text(tl: Dict[str, Any], width: int = 40) -> str:
    """ASCII waterfall of :func:`assemble_timeline`'s output — shared
    by the collector's ``/timeline?format=text`` and the offline
    ``tools/trace_timeline.py``."""
    rows = tl.get("events", [])
    if not rows:
        return f"span {tl.get('span')}: no events\n"
    dur = max(tl.get("duration_s") or 0.0, 1e-9)
    lines = [f"span {tl['span']}: {len(rows)} event(s) across "
             f"{len(tl['origins'])} origin(s) "
             f"({', '.join(tl['origins'])}), {dur * 1e3:.2f} ms"]
    owidth = max(len(r["origin"]) for r in rows)
    kwidth = max(len(str(r["kind"])) for r in rows)
    for r in rows:
        pos = min(width - 1, int(r["offset_s"] / dur * (width - 1)))
        bar = "." * pos + "|" + "." * (width - 1 - pos)
        detail = ""
        if r["detail"]:
            short = {k: r["detail"][k] for k in sorted(r["detail"])[:3]}
            detail = " " + json.dumps(short, sort_keys=True,
                                      default=repr)[:60]
        lines.append(f"  {r['offset_s'] * 1e3:9.3f}ms [{bar}] "
                     f"{r['origin']:<{owidth}} {str(r['kind']):<{kwidth}}"
                     f"{detail}")
    return "\n".join(lines) + "\n"


# -- the daemon ---------------------------------------------------------------


class TelemetryCollector:
    """The push-ingest + alert-eval + read-endpoint daemon (in-process
    form; ``python -m paddle_tpu.telemetry.collector`` wraps exactly
    this). See the module docstring for the wire and HTTP surfaces."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 rules: Optional[List[_alerts.AlertRule]] = None,
                 eval_interval: float = 0.25,
                 journal_ring: int = 16384,
                 max_points: int = 512,
                 origin_expiry_s: float = 60.0,
                 dump_on_fire=None,
                 flight_root: Optional[str] = None,
                 store_dir: Optional[str] = None,
                 retention_s: float = 24 * 3600.0,
                 retention_bytes: int = 256 << 20,
                 segment_max_bytes: int = 4 << 20,
                 segment_max_s: float = 600.0,
                 standby: bool = False,
                 takeover_s: float = 5.0,
                 replicate_from: Optional[Any] = None,
                 replicate_interval: float = 0.5):
        self.store = SeriesStore(max_points=max_points,
                                 origin_expiry_s=origin_expiry_s)
        # the collector's OWN journal (never the process default): it
        # holds the INGESTED fleet-wide stream plus alert transitions,
        # and a collector embedded in a test/trainer process must not
        # bleed into that process's journal
        self.journal = RunJournal(ring_size=journal_ring)
        self.engine = _alerts.AlertEngine(
            rules if rules is not None else _alerts.preset_rules(),
            on_transition=self._on_transition)
        self.eval_interval = float(eval_interval)
        # dump_on_fire: True = every firing transition dumps, False =
        # never, None (default) = page-severity rules dump
        self.dump_on_fire = dump_on_fire
        self._recorder = FlightRecorder(journal=self.journal,
                                        root=flight_root)
        self._lock = threading.Lock()
        # serializes one EVENTS batch's whole read-filter-ingest-update
        # against another's: a stalled handler thread racing its own
        # retry must not double-ingest (the idempotency contract)
        self._ingest_lock = threading.Lock()
        # (origin, run) -> (high-water ship-seq, last touch): EVENTS
        # dedupe (idempotent ingest makes shipper retries safe
        # server-side). Entries untouched for origin_expiry_s are
        # pruned by the eval loop: a STABLY-NAMED origin that restarts
        # mints a new run id per incarnation and must not leak a dead
        # run's entry per restart forever
        self._high: Dict[Tuple[str, str], Tuple[int, float]] = {}
        self._counters = {"events": 0, "snapshots": 0, "event_batches": 0,
                          "dup_events": 0, "bad_requests": 0,
                          "segments_corrupt": 0}
        self._stop = threading.Event()
        self._http: Optional[Any] = None

        # -- durable series store (telemetry/store.py) -------------------
        # With store_dir every ingest is written through to a
        # segmented, CRC-framed, retention-bounded log; a restart (or a
        # standby promotion) replays it to rebuild rings, dedupe
        # high-water marks, the fleet journal, and alert firing/pending
        # state. _seg_lock makes [counter update → log append] atomic
        # across threads so a 'state' record's absolute counters always
        # agree with its position in the log (replay = baseline +
        # increments, exact).
        self._seg: Optional[Any] = None
        self._seg_lock = threading.Lock()
        self._promote_lock = threading.Lock()
        # one-way flag (True -> False exactly once, in promote() under
        # _promote_lock): the hot paths read it lock-free and promote()
        # re-checks under the lock, so a stale True only costs one extra
        # promote() call
        self._standby = bool(standby)   # lint: allow(thread:unguarded-access)
        # the split-brain fence: a standby only promotes once the
        # active writer's heartbeat (stamped every eval tick, removed
        # on clean close) has been silent this long — a transient
        # primary stall that made ONE flush fail over must not create
        # two writers on the shared store_dir
        self.takeover_s = float(takeover_s)
        self._last_retention = 0.0
        if store_dir:
            from .store import SegmentStore
            self._seg = SegmentStore(
                store_dir, retention_s=retention_s,
                retention_bytes=retention_bytes,
                segment_max_bytes=segment_max_bytes,
                segment_max_s=segment_max_s,
                state_fn=self._state_payload)
            if not self._standby:
                self._recover()
                self._seg.open()
        elif self._standby:
            raise ValueError("standby=True needs a store_dir to promote "
                             "from (a standby without a shared segment "
                             "log has no history to adopt)")

        # -- cross-host replication (telemetry catch-up) -----------------
        # A standby on ANOTHER machine cannot share the primary's
        # store_dir; replicate_from="host:port" (the primary's push
        # wire) makes it pull sealed segments + the open-segment tail
        # over the SEGMENTS verb into its OWN store_dir, continuously.
        # Promotion then replays the local replica — and the fence
        # moves from heartbeat-file stamps (meaningless across hosts)
        # to the replication stream: a standby refuses to promote
        # while its replication source still answers a direct probe.
        self._repl_addr: Optional[Tuple[str, int]] = None
        self._repl_cli: Optional[Any] = None
        self._repl_interval = float(replicate_interval)
        self._repl_last_contact: Optional[float] = None
        self._repl_lock = threading.Lock()
        if replicate_from:
            if not self._standby or self._seg is None:
                raise ValueError(
                    "replicate_from= needs standby=True and a (local) "
                    "store_dir — replication is the cross-host standby's "
                    "copy of the primary's segment log")
            from .shipper import parse_addr
            self._repl_addr = parse_addr(replicate_from)
            self._repl_thread = threading.Thread(
                target=self._replicate_loop, daemon=True,
                name="pdtpu-collector-repl")
            self._repl_thread.start()

        self._ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._ls.bind((host, int(port)))
        self._ls.listen(64)
        self.host = host
        self.port = self._ls.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="pdtpu-collector-accept")
        self._accept_thread.start()
        self._eval_thread = threading.Thread(
            target=self._eval_loop, daemon=True, name="pdtpu-collector-eval")
        self._eval_thread.start()

    # -- lifecycle -----------------------------------------------------------

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def close(self) -> None:
        self._stop.set()
        try:
            self._ls.close()
        except OSError:
            pass
        if self._http is not None:
            self._http.close()
            self._http = None
        self._eval_thread.join(timeout=5.0)
        if self._repl_cli is not None:
            try:
                self._repl_cli.close()
            except Exception:
                pass
            self._repl_cli = None
        if self._seg is not None:
            # a final state record makes a CLEAN shutdown bit-exact on
            # restart even when the last eval tick predates the last
            # ingest; then fsync-close the active segment and drop the
            # writer heartbeat so a standby may take over immediately
            with self._seg_lock:
                if not self._standby:
                    self._seg.append(self._state_record())
            self._seg.close()
            if not self._standby:
                self._seg.clear_heartbeat()

    # -- durable store: write-through, recovery, promotion -------------------

    @property
    def persistent(self) -> bool:
        return self._seg is not None

    @property
    def is_standby(self) -> bool:
        return self._standby

    def _state_payload(self) -> Dict[str, Any]:
        """The 'state' record body (minus the ``k`` tag): absolute
        ingest counters, EVENTS dedupe high-water marks, and the alert
        engine's firing/pending/resolved state — everything replay
        cannot reconstruct from snap/ev records alone."""
        with self._lock:
            ctrs = dict(self._counters)
            high = [[o, r, hw, touched]
                    for (o, r), (hw, touched) in sorted(self._high.items())]
        # rule SPECS ride along so a hot-reloaded pack survives restart
        # and standby promotion (the log, not the boot-time --rules
        # file, is the source of truth for a recovering collector)
        specs = [{"name": r.name, "expr": r.expr, "severity": r.severity,
                  "annotations": dict(r.annotations)}
                 for r in self.engine.rules]
        return {"ctrs": ctrs, "high": high, "rules": specs,
                "engine": self.engine.state()}

    def _state_record(self) -> Dict[str, Any]:
        rec = self._state_payload()
        rec["k"] = "state"
        rec["t"] = time.time()
        return rec

    def _apply_record(self, kind: str, doc: Dict[str, Any]) -> None:
        """Replay one persisted record into the in-memory planes (the
        ``SegmentStore.recover`` callback)."""
        if kind == "snap":
            self.store.ingest(str(doc.get("o", "")), doc.get("f") or {},
                              t=doc.get("t"), sanitized=True)
            self._counters["snapshots"] += 1
        elif kind == "ev":
            origin = str(doc.get("o", ""))
            events = doc.get("e") or []
            self.journal.ingest(events, origin=origin)
            key = (origin, str(doc.get("r", "")))
            hw = int(doc.get("hw", 0))
            t = doc.get("t")
            t = float(t) if isinstance(t, (int, float)) else time.time()
            old = self._high.get(key, (0, 0.0))[0]
            self._high[key] = (max(old, hw), t)
            self.store.mark_push(origin, t=t)
            self._counters["events"] += len(events)
            self._counters["event_batches"] += 1
        elif kind == "retire":
            self.store.retire(str(doc.get("o", "")))
        elif kind == "state":
            for k, v in (doc.get("ctrs") or {}).items():
                # segments_corrupt is NOT restored from the baseline:
                # a still-retained corrupt record is re-detected (and
                # re-counted) by every recovery pass, so carrying the
                # old count forward would grow the monotonic counter
                # by one per restart with zero new corruption
                if k in self._counters and k != "segments_corrupt":
                    self._counters[k] = type(self._counters[k])(v)
            self._high = {(str(o), str(r)): (int(hw), float(touched))
                          for o, r, hw, touched in doc.get("high") or []}
            specs = doc.get("rules")
            if specs:
                try:
                    # assigned directly (not set_rules): replay must
                    # never EMIT transitions, and restore() below
                    # replaces the instance table wholesale anyway
                    self.engine.rules = _alerts.parse_rules(specs)
                except _alerts.AlertRuleError:
                    pass  # keep the boot-time rules
            self.engine.restore(doc.get("engine") or {})

    def _recover(self) -> int:
        """Replay the retained segment log oldest → newest. Counter
        exactness: every segment begins with a 'state' record (absolute
        baseline) and subsequent snap/ev records increment, so any
        retained SUFFIX of history recovers the exact pre-restart
        counts. Corrupt records were already skipped (and counted) by
        the store's reader."""
        n = self._seg.recover(self._apply_record)
        self._counters["segments_corrupt"] += \
            self._seg.counters["corrupt_records"]
        if n:
            _log().info("recovered %d telemetry record(s) from %s "
                        "(%d origin(s), %d corrupt record(s) skipped)",
                        n, self._seg.root, len(self.store.origins()),
                        self._seg.counters["corrupt_records"])
        return n

    # -- cross-host replication (standby pull over SEGMENTS) -----------------

    def _repl_client(self):
        if self._repl_cli is None:
            from .shipper import ReplicationClient
            self._repl_cli = ReplicationClient(self._repl_addr)
        return self._repl_cli

    def _replicate_loop(self) -> None:
        while not self._stop.wait(self._repl_interval):
            if not self._standby:
                return  # promoted: this collector writes its own log now
            try:
                self._replicate_once()
            except Exception as e:
                # primary unreachable (dead, partitioned): nothing to
                # pull — retry next tick; promotion decides liveness
                _log().debug("segment replication pull failed: %s: %s",
                             type(e).__name__, e)

    def _replicate_once(self) -> int:
        """One replication pull: list the primary's segments, adopt
        every sealed segment we lack (sidecar-CRC-verified; a segment
        corrupted in flight is rejected and re-requested next cycle),
        then extend the open-segment mirror by exact byte offset.
        Returns the number of sealed segments adopted."""
        with self._repl_lock:
            cli = self._repl_client()
            listing = cli.listing()
            n = 0
            have = self._seg.sealed_names()
            for ent in listing.get("segments") or []:
                name = str(ent.get("name"))
                if name in have:
                    continue
                data = cli.fetch(name)
                if self._seg.ingest_sealed(name, data,
                                           ent.get("meta") or {}):
                    n += 1
            op = listing.get("open")
            if op and op.get("name"):
                name, psize = str(op["name"]), int(op.get("size", 0))
                local = self._seg.mirror_size(name)
                while local < psize:
                    chunk = cli.fetch(name, offset=local,
                                      limit=psize - local)
                    if not chunk:
                        break
                    new = self._seg.ingest_open_tail(name, local, chunk)
                    if new <= local:
                        break
                    local = new
            self._repl_last_contact = time.monotonic()
            return n

    def _primary_reachable(self) -> bool:
        """One direct probe of the replication source — the cross-host
        half of the split-brain fence. True means a live (or returned)
        primary still owns the pen; a standby must not promote over
        it."""
        if self._repl_addr is None:
            return False
        from .shipper import ReplicationClient
        try:
            cli = ReplicationClient(self._repl_addr,
                                    timeout=min(1.0, max(self.takeover_s,
                                                         0.1)))
            try:
                cli.ping()
                return True
            finally:
                cli.close()
        except Exception:
            return False

    def promote(self, force: bool = False) -> bool:
        """Standby → active: replay the shared segment log (rings,
        journal, dedupe marks, alert state — firing instances come back
        firing WITHOUT a new transition) and take over appending to it.
        Idempotent; called automatically on the first data push a
        standby receives (the shipper failed over), or explicitly by an
        operator (``force=True`` skips the fence). Returns True if
        this call did the promotion.

        The fence: promotion REFUSES (raises — the push gets an ERR,
        the shipper re-buffers and retries) while the active writer's
        heartbeat is fresher than ``takeover_s``. One transiently
        stalled primary flush must not let a standby seize the shared
        log out from under a live writer (split-brain: two appenders,
        duplicate alerts, a sidecar CRC committed over a file the
        primary still has open). A dead primary stops stamping, so the
        fence clears within ``takeover_s``; a CLEAN shutdown removes
        the stamp and the takeover is immediate."""
        with self._promote_lock:
            if not self._standby:
                return False
            if self._seg is not None:
                if not force:
                    age = self._seg.heartbeat_age()
                    if age is not None and age < self.takeover_s:
                        raise RuntimeError(
                            f"standby not promoting: the active "
                            f"writer's heartbeat is {age:.1f}s old "
                            f"(< takeover_s={self.takeover_s:g}) — "
                            "retry after it goes silent")
                    # the cross-host fence: with replicate_from the
                    # heartbeat file lives in the PRIMARY's store_dir
                    # on another machine — liveness is the replication
                    # stream itself. A returning primary that answers
                    # a direct probe keeps the pen; exactly one writer.
                    if self._primary_reachable():
                        raise RuntimeError(
                            "standby not promoting: the replication "
                            f"source at {self._repl_addr} still answers "
                            "its wire — a live primary keeps the pen "
                            "(force=True overrides)")
                if self._repl_addr is not None:
                    # final catch-up pull: anything the primary sealed
                    # or appended after our last tick and before its
                    # death. Best-effort — a dead primary fails fast
                    # and we promote from what already replicated.
                    try:
                        self._replicate_once()
                    except Exception:
                        pass
                self._recover()
                self._seg.open()
            self._standby = False
            self.journal.emit("collector.promoted",
                              store=self._seg.root if self._seg else None)
            _log().warning("standby collector promoted "
                           "(store=%s, %d origin(s), %d firing alert(s) "
                           "restored)",
                           self._seg.root if self._seg else None,
                           len(self.store.origins()),
                           len(self.engine.firing()))
            return True

    def _seg_append(self, record: Dict[str, Any]) -> None:
        if self._seg is not None and not self._standby:
            with self._seg_lock:
                self._seg.append(record)

    def __enter__(self) -> "TelemetryCollector":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- push wire -----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._ls.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name="pdtpu-collector-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        from ..parallel.async_ps import read_exact, read_line

        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(30.0)
            while not self._stop.is_set():
                try:
                    line = read_line(conn)
                except (ConnectionError, OSError):
                    return
                parts = line.split()
                if not parts or parts[0] == "QUIT":
                    return
                try:
                    reply = self._dispatch(parts, conn, read_exact)
                except (ConnectionError, OSError):
                    return
                except Exception as e:
                    # a malformed header/body may have left its framed
                    # payload UNREAD: reply ERR and close — keeping the
                    # connection would parse leftover body bytes as the
                    # next header and desync every later request (the
                    # shipper's FramedClient reconnects transparently)
                    with self._lock:
                        self._counters["bad_requests"] += 1
                    reply = f"ERR {type(e).__name__}: {e}"[:200].replace(
                        "\n", " ")
                    try:
                        conn.sendall(reply.encode() + b"\n")
                    except OSError:
                        pass
                    return
                if reply is None:
                    continue  # the branch replied itself (SEGMENTS)
                try:
                    conn.sendall(reply.encode() + b"\n")
                except OSError:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, parts: List[str], conn, read_exact
                  ) -> Optional[str]:
        verb = parts[0]
        if verb == "PING":
            return "OK 0"
        if verb == "SEGMENTS":
            # segment replication (standby pull): {"list": true} → the
            # sealed-segment + open-tail listing; {"fetch": name,
            # "offset": k[, "limit": n]} → raw segment bytes. The
            # branch frames its own reply body (json OR raw bytes) and
            # returns None so _serve_conn sends nothing further.
            if self._seg is None:
                raise ValueError("SEGMENTS needs a collector with a "
                                 "store_dir (no segment log here)")
            req = json.loads(read_exact(conn, int(parts[1])))
            if req.get("fetch"):
                limit = req.get("limit")
                data = self._seg.read_segment(
                    str(req["fetch"]), offset=int(req.get("offset", 0)),
                    limit=None if limit is None else int(limit))
                _reply_json(conn, data)
            else:
                _reply_json(conn, self._seg.replication_listing())
            return None
        if verb == "STATS":
            # ingest/store counters as one JSON object riding the reply
            # line — the bench rows' store-overhead delta source (and a
            # doctor read for operators without the HTTP port)
            return "OK " + json.dumps(self.stats(), sort_keys=True,
                                      separators=(",", ":"))
        if verb in ("EVENTS", "SNAPSHOT") and parts[1] == "collector":
            # reserved: the merged export stamps the collector's OWN
            # series under this origin — a pusher claiming it would be
            # silently overwritten there while still feeding the rings
            # (scrape and alert state would disagree)
            raise ValueError("origin 'collector' is reserved")
        if verb in ("EVENTS", "SNAPSHOT") and self._standby:
            # first data push to a standby: the shippers failed over,
            # so the primary is gone — replay the shared log and take
            # over BEFORE applying this push (its dedupe depends on
            # the replayed high-water marks)
            self.promote()
        if verb == "EVENTS":
            origin, blen = parts[1], int(parts[2])
            body = json.loads(read_exact(conn, blen))
            return f"OK {self._ingest_events(origin, body)}"
        if verb == "SNAPSHOT":
            origin, blen = parts[1], int(parts[2])
            body = json.loads(read_exact(conn, blen))
            t = time.time()
            snap = SeriesStore._sanitize(body.get("families") or {})
            n = self.store.ingest(origin, snap, t=t, sanitized=True)
            with self._seg_lock:
                with self._lock:
                    self._counters["snapshots"] += 1
                if self._seg is not None and not self._standby:
                    self._seg.append({"k": "snap", "o": origin, "t": t,
                                      "f": snap})
            return f"OK {n}"
        # raised (not returned) so the connection CLOSES: an unknown
        # verb from a newer client may carry a framed body this
        # version cannot size — reading on would desync the stream
        raise ValueError(f"unknown verb {verb!r}")

    def _ingest_events(self, origin: str, body: Dict[str, Any]) -> int:
        run = str(body.get("run", ""))
        events = [e for e in body.get("events", [])
                  if isinstance(e, dict) and "kind" in e]
        key = (origin, run)
        # the dedupe mark: a shipper stamps each event with ``sseq``
        # (assigned at buffer-append time, monotonic in ship order
        # even when journal subscribers fire out of journal-seq order,
        # stable across retries); a third-party pusher without it
        # falls back to the journal seq — correct as long as it ships
        # in order
        with self._ingest_lock:
            with self._lock:
                high = self._high.get(key, (0, 0.0))[0]
            fresh = []
            for e in events:
                mark = e.pop("sseq", None)
                if mark is None:
                    mark = e.get("seq")
                if mark is None:
                    # no dedupe mark at all: ingest rather than drop
                    # (dedupe is impossible for such a pusher — a
                    # retried unmarked batch may duplicate, which is
                    # the pusher's trade, not silent loss here)
                    fresh.append(e)
                    continue
                if int(mark) > high:
                    fresh.append(e)
                    high = max(high, int(mark))
            dup = len(events) - len(fresh)
            n = self.journal.ingest(fresh, origin=origin) if fresh else 0
            now = time.time()
            with self._seg_lock:
                with self._lock:
                    self._counters["events"] += n
                    self._counters["dup_events"] += dup
                    self._counters["event_batches"] += 1
                    self._high[key] = (max(self._high.get(key, (0, 0.0))[0],
                                           high), now)
                if self._seg is not None and not self._standby:
                    # written BEFORE the OK reply goes out: an event
                    # batch the shipper saw acknowledged is durable, so
                    # a standby replaying this log dedupes the resend a
                    # failed-over shipper makes of anything UNACKED
                    self._seg.append({"k": "ev", "o": origin, "t": now,
                                      "r": run, "hw": high, "e": fresh})
        self.store.mark_push(origin, t=now)
        return n

    # -- alert evaluation ----------------------------------------------------

    def _eval_loop(self) -> None:
        while not self._stop.wait(self.eval_interval):
            try:
                self.evaluate_once()
            except Exception as e:  # the watchtower must not fall over
                _log().warning("alert evaluation failed: %s: %s",
                               type(e).__name__, e)

    def evaluate_once(self, now: Optional[float] = None
                      ) -> List[Dict[str, Any]]:
        """One expiry + evaluation tick (the eval thread's body; tests
        and drills call it directly for deterministic timing). A
        standby does NOTHING here: it must not expire origins, fire
        alerts, or touch the shared log the primary is writing."""
        if self._standby:
            return []
        now = time.time() if now is None else now
        retired = self.store.expire(now)
        for origin in retired:
            self.journal.emit("collector.origin_retired", origin=origin)
            # persisted so replay does not resurrect the retired
            # origin's series from its older snap records
            self._seg_append({"k": "retire", "o": origin, "t": now})
        # dedupe marks are TTL-pruned, not only origin-retired: a
        # stably-named origin that restarts mints a new run id per
        # incarnation while keeping its last_push fresh, so dead runs'
        # entries would otherwise leak forever on a long-lived
        # collector (a rejoining run re-ships its ring and dedupes
        # from scratch — idempotent-safe)
        gone = set(retired)
        with self._lock:
            for key in [k for k, (_, touched) in self._high.items()
                        if k[0] in gone or
                        now - touched > self.store.origin_expiry_s]:
                del self._high[key]
        transitions = self.engine.evaluate(self.store, now)
        if self._seg is not None:
            self._seg.touch_heartbeat()
            # retention re-lists the dir and re-reads every sealed
            # sidecar — a per-tick sweep would be hundreds of
            # syscalls/s under the store lock for a bound that moves
            # on the scale of segments, so it runs every ~10s
            if now - self._last_retention >= 10.0:
                self._last_retention = now
                self._seg.enforce_retention(now)
            self._persist_state_if_changed()
        return transitions

    def _persist_state_if_changed(self) -> None:
        """Append a 'state' record when anything it captures moved
        since the last tick (ingest counters, dedupe marks, alert
        instances) — idle collectors write nothing, loaded ones write
        one small record per eval tick."""
        with self._seg_lock:
            rec = self._state_record()
            fp = json.dumps({"ctrs": rec["ctrs"], "high": rec["high"],
                             "engine": rec["engine"]},
                            sort_keys=True, default=repr)
            if fp == getattr(self, "_last_state_fp", None):
                return
            self._last_state_fp = fp
            self._seg.append(rec)

    def _on_transition(self, t: Dict[str, Any]) -> None:
        self.journal.emit(f"alert.{t['state']}", rule=t["rule"],
                          key=t["key"], value=t.get("value"),
                          severity=t["severity"], expr=t["expr"])
        _log().warning("alert %s: %s on %s (value=%s)", t["state"],
                       t["rule"], t["key"], t.get("value"))
        if t["state"] == "firing" and (
                self.dump_on_fire is True or
                (self.dump_on_fire is None and t["severity"] == "page")):
            # the pager moment: flush the fleet-wide ring to disk so
            # the evidence exists even if the collector dies next
            self._recorder.dump(f"alert_{t['rule']}", detail=t,
                                span=None)

    # -- read surfaces -------------------------------------------------------

    def families(self, now: Optional[float] = None) -> List[MetricFamily]:
        """ONE merged export: every origin's latest snapshot + the
        collector's own series (stamped ``origin="collector"``) through
        a single :func:`merge_exports` pass, so family declarations
        never repeat and the naming contract holds.

        An origin silent past HALF its expiry scrapes with a
        ``stale="true"`` label on every sample (the JSON form carries
        the same label): its gauges are the last thing a dead process
        said, and an autoscaler reading the merged export must be able
        to tell a fresh 'queue_depth 0' from a frozen one BEFORE the
        origin is retired wholesale."""
        now = time.time() if now is None else now
        with self._lock:
            c = dict(self._counters)
        snap = self.engine.snapshot()
        firing = len(snap["firing"])
        trans = snap["transitions_total"]
        own = [
            counter_family("paddle_tpu_collector_events_total",
                           "Journal events ingested from shippers",
                           [({}, c["events"])]),
            counter_family("paddle_tpu_collector_snapshots_total",
                           "Metric snapshots ingested from shippers",
                           [({}, c["snapshots"])]),
            gauge_family("paddle_tpu_collector_origins",
                         "Origins currently pushing telemetry",
                         [({}, len(self.store.origins()))]),
            gauge_family("paddle_tpu_collector_alerts_firing",
                         "Alert instances currently firing",
                         [({}, firing)]),
            counter_family("paddle_tpu_collector_alert_transitions_total",
                           "Alert state transitions (by state)",
                           [({"state": s}, v)
                            for s, v in sorted(trans.items())]),
        ]
        if self._seg is not None:
            sc = dict(self._seg.counters)
            own += [
                counter_family(
                    "paddle_tpu_collector_segments_corrupt_total",
                    "Corrupt segment records detected and skipped by "
                    "recovery (CRC mismatch, torn tail, bitrot)",
                    [({}, c["segments_corrupt"])]),
                counter_family(
                    "paddle_tpu_collector_store_appends_total",
                    "Records appended to the on-disk series store",
                    [({}, sc["appends"])]),
                counter_family(
                    "paddle_tpu_collector_store_bytes_total",
                    "Bytes appended to the on-disk series store",
                    [({}, sc["bytes"])]),
                counter_family(
                    "paddle_tpu_collector_store_append_seconds_total",
                    "Seconds spent in store appends (ingest-write "
                    "overhead)",
                    [({}, round(sc["append_seconds"], 6))]),
                counter_family(
                    "paddle_tpu_collector_store_append_failures_total",
                    "Store appends that failed (disk full/IO error) — "
                    "pushes were still ACKed from memory, so a nonzero "
                    "rate means the durable log is falling behind",
                    [({}, sc["append_failures"])]),
                gauge_family(
                    "paddle_tpu_collector_store_segments",
                    "Retained segments on disk (active included)",
                    [({}, len(self._seg.segment_paths()))]),
            ]
        stale_after = self.store.origin_expiry_s / 2.0
        ages = self.store.origins()
        named = {}
        for origin, osnap in self.store.latest_snapshots().items():
            fams = families_from_snapshot(osnap)
            if now - ages.get(origin, now) > stale_after:
                for fam in fams:
                    fam.samples = [(dict(labels, stale="true"), value)
                                   for labels, value in fam.samples]
            named[origin] = fams
        named["collector"] = own
        return merge_exports(named, label="origin")

    def stats(self) -> Dict[str, Any]:
        """Flat ingest/store counters (the ``STATS`` wire verb body —
        the bench rows delta these to price store ingest-writes)."""
        with self._lock:
            out: Dict[str, Any] = dict(self._counters)
        out["origins"] = len(self.store.origins())
        out["standby"] = self._standby
        out["persistence"] = self._seg is not None
        if self._seg is not None:
            sc = dict(self._seg.counters)
            out["store"] = {
                "appends": sc["appends"], "bytes": sc["bytes"],
                "append_seconds": round(sc["append_seconds"], 6),
                "append_failures": sc["append_failures"],
                "segments_sealed": sc["segments_sealed"],
                "segments_deleted": sc["segments_deleted"],
                "segments": len(self._seg.segment_paths()),
                "repl_segments": sc["repl_segments"],
                "repl_bytes": sc["repl_bytes"],
                "repl_corrupt": sc["repl_corrupt"],
            }
        out["replicating"] = self._repl_addr is not None
        if self._repl_addr is not None:
            with self._repl_lock:
                last = self._repl_last_contact
            out["repl_contact_age_s"] = (
                None if last is None
                else round(time.monotonic() - last, 3))
        return out

    def query(self, metric: str, labels: Optional[Dict[str, str]] = None,
              start: float = 0.0, end: Optional[float] = None,
              step: float = 0.0) -> Dict[str, Any]:
        """Range-read one metric (the ``GET /query`` body): from the
        durable segment log when persistence is on — the answer then
        survives this collector — else from the bounded in-memory
        rings."""
        if self._seg is not None:
            return self._seg.query(metric, labels, start=start, end=end,
                                   step=step)
        return self.store.range_query(metric, labels, start=start, end=end,
                                      step=step)

    def reload_rules(self, specs: Optional[List[Dict[str, Any]]] = None,
                     path: Optional[str] = None) -> List[str]:
        """Hot-reload the alert rule pack (SIGHUP / ``POST /rules``):
        lint first (:func:`~paddle_tpu.telemetry.alerts.lint_rules`),
        REJECT on any finding (returned; the running rules stay in
        force), else swap via ``AlertEngine.set_rules`` — state keyed
        by rule name survives, firing instances of removed rules
        resolve — and journal ``alert.rules_reloaded``."""
        if (specs is None) == (path is None):
            raise ValueError("pass exactly one of specs= or path=")
        if path is not None:
            try:
                with open(path, "r", encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, ValueError) as e:
                return [f"alert:malformed-expr {path}: unreadable rule "
                        f"file: {e}"]
            specs = doc.get("rules", []) if isinstance(doc, dict) else doc
        if not isinstance(specs, list):
            return ["alert:malformed-expr expected a JSON list of rules "
                    "(or {'rules': [...]})"]
        findings = _alerts.lint_rules(specs)
        if findings:
            self.journal.emit("alert.rules_rejected", findings=len(findings),
                              source=path or "<inline>")
            _log().warning("alert rule reload REJECTED (%d finding(s); "
                           "running rules stay in force)", len(findings))
            return findings
        rules = _alerts.parse_rules(specs)
        self.engine.set_rules(rules)
        self.journal.emit("alert.rules_reloaded", rules=len(rules),
                          names=sorted(r.name for r in rules),
                          source=path or "<inline>")
        _log().info("alert rules reloaded: %d rule(s)", len(rules))
        if self._seg is not None and not self._standby:
            with self._seg_lock:
                self._last_state_fp = None
                self._seg.append(self._state_record())
        return []

    def alerts_json(self) -> Dict[str, Any]:
        return self.engine.snapshot()

    def timeline(self, span: str) -> Dict[str, Any]:
        return assemble_timeline(self.journal.recent(), span)

    def serve_http(self, port: int = 0, host: Optional[str] = None):
        """Start the read endpoint: ``/metrics`` + ``/healthz`` +
        ``/alerts`` + ``/timeline?trace=<span>[&format=text]``.
        Idempotent; returns the :class:`~paddle_tpu.telemetry.http.
        TelemetryServer` (``.url``/``.port``)."""
        from .http import serve_metrics
        from .registry import FamiliesView

        if self._http is not None:
            return self._http

        def health():
            return {"live": not self._stop.is_set(),
                    "role": "standby" if self._standby else "collector",
                    "persistence": self._seg is not None,
                    "origins": sorted(self.store.origins()),
                    "alerts_firing": len(self.engine.firing())}

        def alerts_route(query: str):
            body = json.dumps(self.alerts_json(), sort_keys=True,
                              default=repr).encode()
            return 200, "application/json", body

        def timeline_route(query: str):
            params = dict(p.partition("=")[::2]
                          for p in query.split("&") if p)
            span = params.get("trace") or params.get("span")
            if not span:
                return (400, "text/plain; charset=utf-8",
                        b"need ?trace=<span>\n")
            tl = self.timeline(span)
            if params.get("format") == "text":
                return (200, "text/plain; charset=utf-8",
                        render_timeline_text(tl).encode())
            return (200, "application/json",
                    json.dumps(tl, sort_keys=True, default=repr).encode())

        def query_route(query: str):
            params = dict(p.partition("=")[::2]
                          for p in query.split("&") if p)
            metric = params.get("metric")
            if not metric:
                return (400, "text/plain; charset=utf-8",
                        b"need ?metric=<name>[&labels=k=v,k2=v2]"
                        b"[&from=T][&to=T][&step=S]\n")
            try:
                labels = _alerts._parse_labels(params.get("labels"))
                start = float(params.get("from", 0.0))
                end = (float(params["to"]) if params.get("to") is not None
                       and params.get("to") != "" else None)
                step = float(params.get("step", 0.0))
            except (ValueError, _alerts.AlertRuleError) as e:
                return (400, "text/plain; charset=utf-8",
                        f"bad query parameter: {e}\n".encode())
            doc = self.query(metric, labels, start=start, end=end,
                             step=step)
            return (200, "application/json",
                    json.dumps(doc, sort_keys=True, default=repr).encode())

        def rules_post(query: str, body: bytes):
            try:
                specs = json.loads(body.decode("utf-8"))
            except (ValueError, UnicodeDecodeError) as e:
                return (400, "application/json",
                        json.dumps({"accepted": False, "findings": [
                            f"alert:malformed-expr body is not JSON: {e}"
                        ]}).encode())
            if isinstance(specs, dict):
                specs = specs.get("rules", [])
            findings = self.reload_rules(specs=specs)
            doc = {"accepted": not findings, "findings": findings,
                   "rules": [r.describe() for r in self.engine.rules]}
            return (200 if not findings else 422, "application/json",
                    json.dumps(doc, sort_keys=True).encode())

        self._http = serve_metrics(
            registry=FamiliesView(self.families), health_fn=health,
            port=port, host=host or self.host,
            extra_routes={"/alerts": alerts_route,
                          "/timeline": timeline_route,
                          "/query": query_route},
            post_routes={"/rules": rules_post})
        return self._http


# -- out-of-process spawn -----------------------------------------------------


class CollectorProcess:
    """Spawn-and-own a standalone collector daemon (``python -m
    paddle_tpu.telemetry.collector``); parses the ``PORT``/``HTTP``
    handshake. ``addr`` is the push wire, ``http_port`` the read
    endpoint."""

    def __init__(self, rules_path: Optional[str] = None,
                 host: str = "127.0.0.1", args: Tuple[str, ...] = (),
                 store_dir: Optional[str] = None,
                 timeout: float = 300.0):
        # timeout matches ReplicaProcess.wait_ready: the child's cold
        # interpreter + package import can take minutes on a machine
        # already saturated by a test suite or a training fleet
        import os
        import select
        import subprocess
        import sys

        from ..parallel.async_ps import child_python_env

        argv = [sys.executable, "-m", "paddle_tpu.telemetry.collector",
                "--host", host, "--port", "0", "--http-port", "0"]
        if rules_path:
            argv += ["--rules", rules_path]
        if store_dir:
            argv += ["--store-dir", store_dir]
        argv += list(args)
        # a collector child must never ship to itself (or to whatever
        # collector the PARENT ships to — its metrics are its own)
        env = child_python_env(pop=("PDTPU_TELEMETRY_ADDR",
                                    "PDTPU_TELEMETRY_ORIGIN"))
        self._proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                      text=True, env=env)
        self.host = host
        self.port: Optional[int] = None
        self.http_port: Optional[int] = None
        # the pipe is select()ed so the deadline holds even when the
        # child hangs WITHOUT printing (the wait_ready discipline) —
        # and a stalled handshake must not orphan the live daemon the
        # caller has no handle to. Reads are raw os.read on the fd,
        # NOT readline(): when the PORT and HTTP lines land in one
        # pipe chunk, readline() would buffer the second line inside
        # the TextIOWrapper where select() cannot see it — and the
        # handshake would hang on a pipe that already delivered
        # everything (a real observed flake, timing-dependent).
        deadline = time.monotonic() + timeout
        fd = self._proc.stdout.fileno()
        buf = b""
        while self.port is None or self.http_port is None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.stop()
                raise TimeoutError(
                    f"collector did not hand shake in {timeout:g}s")
            ready, _, _ = select.select([fd], [], [],
                                        min(remaining, 1.0))
            if not ready:
                continue
            chunk = os.read(fd, 4096)
            if not chunk:
                raise RuntimeError(
                    f"collector process exited rc={self._proc.poll()} "
                    "before its handshake")
            buf += chunk
            while b"\n" in buf and (self.port is None or
                                    self.http_port is None):
                line, _, buf = buf.partition(b"\n")
                text = line.decode("utf-8", "replace")
                if text.startswith("PORT "):
                    self.port = int(text.split()[1])
                elif text.startswith("HTTP "):
                    self.http_port = int(text.split()[1])

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.host, self.port)

    @property
    def http_url(self) -> str:
        return f"http://{self.host}:{self.http_port}"

    @property
    def pid(self) -> int:
        return self._proc.pid

    def kill(self) -> None:
        """SIGKILL, no cleanup — the HA drill's primary-death injector
        (``stop()`` is the graceful path)."""
        import signal as _signal

        if self._proc.poll() is None:
            try:
                self._proc.send_signal(_signal.SIGKILL)
            except OSError:
                pass
            try:
                self._proc.wait(timeout=5.0)
            except Exception:
                pass

    def stop(self) -> None:
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5.0)
            except Exception:
                self._proc.kill()

    def __enter__(self) -> "CollectorProcess":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import signal

    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.telemetry.collector",
        description="telemetry collector daemon: push ingest wire + "
                    "/metrics /alerts /timeline")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--bind", default="",
                    help="listener bind address for the push wire AND "
                         "the HTTP endpoint (also PDTPU_BIND_ADDR; "
                         "overrides --host; default loopback)")
    ap.add_argument("--port", type=int, default=0,
                    help="push wire port (0 picks free)")
    ap.add_argument("--http-port", type=int, default=0,
                    help="read endpoint port (0 picks free)")
    ap.add_argument("--rules", default="",
                    help="JSON alert-rule file (default: the preset pack; "
                         "SIGHUP re-lints and hot-reloads it)")
    ap.add_argument("--eval-interval", type=float, default=0.25)
    ap.add_argument("--origin-expiry", type=float, default=60.0)
    ap.add_argument("--flight-root", default="",
                    help="flight-dump root for alert-triggered dumps")
    ap.add_argument("--dump-on-fire", action="store_true",
                    help="flight-dump on EVERY firing transition "
                         "(default: page-severity rules only)")
    ap.add_argument("--store-dir", default="",
                    help="segmented on-disk series store root (empty: "
                         "in-memory only, a restart loses history)")
    ap.add_argument("--retention-s", type=float, default=24 * 3600.0,
                    help="store retention by time (oldest sealed "
                         "segments past this are deleted)")
    ap.add_argument("--retention-bytes", type=int, default=256 << 20,
                    help="store retention by size (oldest-first "
                         "deletion past this)")
    ap.add_argument("--segment-max-bytes", type=int, default=4 << 20)
    ap.add_argument("--standby", action="store_true",
                    help="start as an HA standby over the shared "
                         "--store-dir: no ingestion/eval until the "
                         "first failed-over push promotes it (replaying "
                         "the segment log)")
    ap.add_argument("--takeover-s", type=float, default=5.0,
                    help="standby promotion fence: refuse to promote "
                         "while the active writer's heartbeat is "
                         "fresher than this (0 disables)")
    ap.add_argument("--replicate-from", default="",
                    help="primary collector push-wire addr (host:port) "
                         "to replicate the segment log from — the "
                         "cross-host standby form (needs --standby and "
                         "a LOCAL --store-dir)")
    ap.add_argument("--replicate-interval", type=float, default=0.5,
                    help="seconds between replication pulls")
    args = ap.parse_args(argv)

    import os as _os
    host = args.bind or _os.environ.get("PDTPU_BIND_ADDR") or args.host
    rules = _alerts.load_rules(args.rules) if args.rules else None
    col = TelemetryCollector(
        host=host, port=args.port, rules=rules,
        eval_interval=args.eval_interval,
        origin_expiry_s=args.origin_expiry,
        dump_on_fire=True if args.dump_on_fire else None,
        flight_root=args.flight_root or None,
        store_dir=args.store_dir or None,
        retention_s=args.retention_s,
        retention_bytes=args.retention_bytes,
        segment_max_bytes=args.segment_max_bytes,
        standby=args.standby, takeover_s=args.takeover_s,
        replicate_from=args.replicate_from or None,
        replicate_interval=args.replicate_interval)
    http = col.serve_http(port=args.http_port)
    stop = threading.Event()
    hup = threading.Event()
    # handlers are installed BEFORE the PORT/HTTP handshake prints:
    # the handshake means "ready", and an operator (or drill) may
    # SIGHUP the instant it lands — with the default disposition still
    # in place that signal would KILL the daemon (a real observed
    # race: the HTTP thread can hold the GIL through a first scrape
    # while the main thread has not reached signal.signal yet)
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, lambda *a: stop.set())
        except ValueError:  # not the main thread (embedded call)
            break
    try:
        # the SIGHUP contract: re-lint the --rules file and hot-swap
        # the pack; findings REJECT the reload and the running rules
        # stay in force (the reload never leaves the engine rule-less)
        signal.signal(signal.SIGHUP, lambda *a: hup.set())
    except (ValueError, AttributeError):  # embedded call / no SIGHUP
        pass
    print(f"PORT {col.port}", flush=True)
    print(f"HTTP {http.port}", flush=True)
    import sys as _sys
    try:
        while not stop.wait(0.5):
            if hup.is_set():
                hup.clear()
                # reload chatter goes to STDERR: stdout is the
                # handshake pipe a CollectorProcess parent never
                # drains past PORT/HTTP — enough SIGHUPs printing
                # there would fill the pipe and wedge this loop
                if args.rules:
                    findings = col.reload_rules(path=args.rules)
                    for f in findings:
                        print(f"rules reload rejected: {f}",
                              file=_sys.stderr, flush=True)
                else:
                    print("SIGHUP ignored: no --rules file to reload",
                          file=_sys.stderr, flush=True)
    finally:
        col.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())


__all__ = [
    "CollectorProcess", "SeriesStore", "TelemetryCollector",
    "assemble_timeline", "render_timeline_text",
]
