"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up JAX/XLA/pallas re-design with the capabilities of the
reference framework (PaddlePaddle Fluid — see SURVEY.md): layer library,
optimizers with in-step regularization/clipping, functional state,
executor-style training, mesh-sharded data/tensor/sequence/pipeline
parallelism, sparse & sharded embeddings, checkpointing, metrics,
profiling, quantization, RecordIO data format (C++ core), beam-search
decoding, and a StableHLO inference/export path.
"""

from . import _jax_compat  # noqa: F401  — must run before any submodule
from . import analysis, backward, clip, core, data, debugger, evaluator, framework, initializer
from . import io, layers, lr_scheduler, metrics, models, nets, optimizer
from . import parallel, quantize, regularizer, resilience, serving, sparse, telemetry, transpiler
from .resilience import (CheckpointCorrupt, GuardPolicy, PreemptionHandler,
                         ReshardError, reshard_restore)
from .serving import PredictorServer
from .core import CPUPlace, CUDAPlace, Place, TPUPlace, default_place
from .executor import CheckpointConfig, Event, Executor, Inferencer, Scope, Trainer, fit
from .framework import (
    LayerHelper,
    ParamAttr,
    Program,
    WeightNormParamAttr,
    amp_guard,
    build,
    create_parameter,
    create_variable,
    default_main_program,
    default_startup_program,
    name_scope,
    program_guard,
)
from .backward import append_backward, calc_gradient
from .executor import global_scope, scope_guard
from .transpiler import (
    DistributeTranspiler,
    DistributeTranspilerConfig,
    HashName,
    RoundRobin,
    memory_optimize,
    release_memory,
)
from .parallel import DistStrategy, ShardingRules, make_mesh
from .core.config import enable_determinism

# honor PDTPU_DETERMINISTIC=1 before any backend work happens
if core.config.get_flag("deterministic"):
    enable_determinism()

__version__ = "0.1.0"
