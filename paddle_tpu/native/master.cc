// Fault-tolerant dataset task-queue master — the TPU-native equivalent of
// the reference's Go master (go/master/service.go: task lease+timeout
// :341 checkTimeoutFunc, retry-then-discard :313 processFailedTask,
// state snapshot/recover :207/:166). Differences by design: state
// snapshots go to a local/NFS file (atomic rename) instead of etcd, and
// transport is a line-framed TCP protocol instead of Go net/rpc — the
// capability (stateless trainers leasing data shards with crash
// recovery) is the same.
//
// Build: g++ -O2 -std=c++17 -pthread master.cc -o master_server
// Run:   master_server <port> <snapshot_path> <failure_max> <lease_timeout_ms>
//        port 0 picks a free port; the chosen port is printed as
//        "PORT <n>" on stdout.
//
// Protocol (one request per line; payloads length-prefixed, binary-safe):
//   ADD <len>\n<bytes>   -> OK <id>
//   GET                  -> TASK <id> <len>\n<bytes> | WAIT | DONE
//   FIN <id>             -> OK | ERR <msg>
//   FAIL <id>            -> OK (requeue or discard per failure_max)
//   RESET                -> OK <pass>   (requeue all non-discarded; new pass)
//   STATUS               -> OK todo=.. leased=.. done=.. discarded=.. pass=.. total=..
//   QUIT                 -> closes the connection

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

int64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

enum class TaskState { kTodo, kLeased, kDone, kDiscarded };

struct Task {
  int64_t id;
  std::string payload;
  int failures = 0;
  TaskState state = TaskState::kTodo;
  int64_t lease_deadline_ms = 0;
};

class Master {
 public:
  Master(std::string snapshot_path, int failure_max, int64_t lease_timeout_ms)
      : snapshot_path_(std::move(snapshot_path)),
        failure_max_(failure_max),
        lease_timeout_ms_(lease_timeout_ms) {
    Recover();
  }

  std::string Add(const std::string& payload) {
    std::lock_guard<std::mutex> g(mu_);
    Task t;
    t.id = next_id_++;
    t.payload = payload;
    tasks_[t.id] = std::move(t);
    todo_.push_back(next_id_ - 1);
    Snapshot();
    return "OK " + std::to_string(next_id_ - 1) + "\n";
  }

  // Returns the response header; *payload set when a task is leased.
  std::string Get(std::string* payload) {
    std::lock_guard<std::mutex> g(mu_);
    if (todo_.empty()) {
      for (auto& kv : tasks_)
        if (kv.second.state == TaskState::kLeased) return "WAIT\n";
      return "DONE\n";
    }
    int64_t id = todo_.front();
    todo_.pop_front();
    Task& t = tasks_[id];
    t.state = TaskState::kLeased;
    t.lease_deadline_ms = now_ms() + lease_timeout_ms_;
    *payload = t.payload;
    Snapshot();
    return "TASK " + std::to_string(id) + " " +
           std::to_string(t.payload.size()) + "\n";
  }

  std::string Finish(int64_t id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = tasks_.find(id);
    if (it == tasks_.end()) return "ERR unknown task\n";
    if (it->second.state != TaskState::kLeased)
      return "ERR task not leased\n";
    it->second.state = TaskState::kDone;
    Snapshot();
    return "OK\n";
  }

  std::string Fail(int64_t id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = tasks_.find(id);
    if (it == tasks_.end()) return "ERR unknown task\n";
    if (it->second.state != TaskState::kLeased)
      return "OK\n";  // double-fail / already timed out: idempotent
    FailLocked(&it->second);
    Snapshot();
    return "OK\n";
  }

  std::string Reset() {
    std::lock_guard<std::mutex> g(mu_);
    ++pass_;
    todo_.clear();
    for (auto& kv : tasks_) {
      if (kv.second.state == TaskState::kDiscarded) continue;
      kv.second.state = TaskState::kTodo;
      kv.second.failures = 0;
      todo_.push_back(kv.first);
    }
    Snapshot();
    return "OK " + std::to_string(pass_) + "\n";
  }

  std::string Status() {
    std::lock_guard<std::mutex> g(mu_);
    int todo = 0, leased = 0, done = 0, discarded = 0;
    for (auto& kv : tasks_) {
      switch (kv.second.state) {
        case TaskState::kTodo: ++todo; break;
        case TaskState::kLeased: ++leased; break;
        case TaskState::kDone: ++done; break;
        case TaskState::kDiscarded: ++discarded; break;
      }
    }
    char buf[160];
    snprintf(buf, sizeof(buf),
             "OK todo=%d leased=%d done=%d discarded=%d pass=%d total=%zu\n",
             todo, leased, done, discarded, pass_, tasks_.size());
    return buf;
  }

  // checkTimeoutFunc analog: requeue (or discard) expired leases.
  void CheckTimeouts() {
    std::lock_guard<std::mutex> g(mu_);
    int64_t now = now_ms();
    bool changed = false;
    for (auto& kv : tasks_) {
      Task& t = kv.second;
      if (t.state == TaskState::kLeased && t.lease_deadline_ms <= now) {
        FailLocked(&t);
        changed = true;
      }
    }
    if (changed) Snapshot();
  }

 private:
  // processFailedTask analog: retry up to failure_max, then discard.
  void FailLocked(Task* t) {
    ++t->failures;
    if (t->failures >= failure_max_) {
      t->state = TaskState::kDiscarded;
    } else {
      t->state = TaskState::kTodo;
      todo_.push_back(t->id);
    }
  }

  // Atomic snapshot (etcd-save analog): text header + binary payloads.
  void Snapshot() {
    if (snapshot_path_.empty()) return;
    std::string tmp = snapshot_path_ + ".tmp";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (!f) return;
    fprintf(f, "%d %ld %zu\n", pass_, static_cast<long>(next_id_),
            tasks_.size());
    for (auto& kv : tasks_) {
      const Task& t = kv.second;
      fprintf(f, "%ld %d %d %zu\n", static_cast<long>(t.id), t.failures,
              static_cast<int>(t.state), t.payload.size());
      fwrite(t.payload.data(), 1, t.payload.size(), f);
      fputc('\n', f);
    }
    fclose(f);
    rename(tmp.c_str(), snapshot_path_.c_str());
  }

  void Recover() {
    if (snapshot_path_.empty()) return;
    FILE* f = fopen(snapshot_path_.c_str(), "rb");
    if (!f) return;
    size_t n = 0;
    long next_id = 0;
    if (fscanf(f, "%d %ld %zu", &pass_, &next_id, &n) != 3) {
      fclose(f);
      pass_ = 0;  // fscanf may have written a partial header into it
      return;
    }
    fgetc(f);  // exactly the header newline
    // staged all-or-nothing parse (matches pserver.cc Recover): a
    // truncated/corrupt snapshot must not leave a silently partial task
    // set, and a corrupt len field must not bad_alloc the master away
    const size_t kMaxLen = 100u << 20;  // matches the ADD payload cap
    std::map<int64_t, Task> staged;
    bool complete = true;
    for (size_t i = 0; i < n; ++i) {
      long id;
      int failures, state;
      size_t len;
      // no trailing '\n' in the format: scanf's '\n' matches a RUN of
      // whitespace and would swallow leading payload bytes that happen
      // to be 0x09-0x0D/0x20, misaligning every later record
      if (fscanf(f, "%ld %d %d %zu", &id, &failures, &state, &len) != 4 ||
          len > kMaxLen) {
        complete = false;
        break;
      }
      fgetc(f);  // exactly the header newline; payload starts next byte
      Task t;
      t.id = id;
      t.failures = failures;
      t.state = static_cast<TaskState>(state);
      t.payload.resize(len);
      if (len && fread(&t.payload[0], 1, len, f) != len) {
        complete = false;
        break;
      }
      fgetc(f);  // trailing newline
      // leases do not survive a master restart: requeue them
      if (t.state == TaskState::kLeased) t.state = TaskState::kTodo;
      staged[t.id] = std::move(t);
    }
    // an undersized header count (corrupted digit) would parse cleanly
    // and silently drop the tail — the file must be fully consumed
    if (complete && fgetc(f) != EOF) complete = false;
    fclose(f);
    if (!complete) {
      fprintf(stderr,
              "master: snapshot truncated/corrupt (%zu of %zu tasks "
              "readable), starting fresh\n", staged.size(), n);
      pass_ = 0;
      next_id_ = 0;
      return;
    }
    next_id_ = next_id;
    for (auto& kv : staged)
      if (kv.second.state == TaskState::kTodo) todo_.push_back(kv.first);
    tasks_ = std::move(staged);
  }

  std::mutex mu_;
  std::map<int64_t, Task> tasks_;
  std::deque<int64_t> todo_;
  int64_t next_id_ = 0;
  int pass_ = 0;
  std::string snapshot_path_;
  int failure_max_;
  int64_t lease_timeout_ms_;
};

// -- line-framed socket IO ---------------------------------------------------

bool ReadLine(int fd, std::string* line) {
  line->clear();
  char c;
  while (true) {
    ssize_t r = recv(fd, &c, 1, 0);
    if (r <= 0) return false;
    if (c == '\n') return true;
    line->push_back(c);
    if (line->size() > 1 << 20) return false;
  }
}

bool ReadExact(int fd, char* buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = recv(fd, buf + got, n - got, 0);
    if (r <= 0) return false;
    got += r;
  }
  return true;
}

bool WriteAll(int fd, const char* buf, size_t n) {
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = send(fd, buf + sent, n - sent, MSG_NOSIGNAL);
    if (r <= 0) return false;
    sent += r;
  }
  return true;
}

void ServeClient(Master* master, int fd) {
  std::string line;
  while (ReadLine(fd, &line)) {
    std::string resp, payload;
    if (line.rfind("ADD ", 0) == 0) {
      size_t len = strtoull(line.c_str() + 4, nullptr, 10);
      if (len > (100u << 20)) break;
      std::string body(len, '\0');
      if (!ReadExact(fd, &body[0], len)) break;
      resp = master->Add(body);
    } else if (line == "GET") {
      resp = master->Get(&payload);
    } else if (line.rfind("FIN ", 0) == 0) {
      resp = master->Finish(strtoll(line.c_str() + 4, nullptr, 10));
    } else if (line.rfind("FAIL ", 0) == 0) {
      resp = master->Fail(strtoll(line.c_str() + 5, nullptr, 10));
    } else if (line == "RESET") {
      resp = master->Reset();
    } else if (line == "STATUS") {
      resp = master->Status();
    } else if (line == "QUIT") {
      break;
    } else {
      resp = "ERR bad command\n";
    }
    if (!WriteAll(fd, resp.data(), resp.size())) break;
    if (!payload.empty() && !WriteAll(fd, payload.data(), payload.size()))
      break;
  }
  close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr,
            "usage: master_server <port> <snapshot_path> [failure_max] "
            "[lease_timeout_ms]\n");
    return 1;
  }
  int port = atoi(argv[1]);
  std::string snapshot = argv[2];
  if (snapshot == "-") snapshot.clear();
  int failure_max = argc > 3 ? atoi(argv[3]) : 3;
  int64_t lease_ms = argc > 4 ? atoll(argv[4]) : 60000;

  Master master(snapshot, failure_max, lease_ms);

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(port);
  if (bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  socklen_t alen = sizeof(addr);
  getsockname(srv, reinterpret_cast<sockaddr*>(&addr), &alen);
  listen(srv, 64);  // before PORT: clients connect the moment they see it
  printf("PORT %d\n", ntohs(addr.sin_port));
  fflush(stdout);

  std::thread timeout_thread([&master]() {
    while (true) {
      std::this_thread::sleep_for(std::chrono::milliseconds(200));
      master.CheckTimeouts();
    }
  });
  timeout_thread.detach();

  while (true) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::thread(ServeClient, &master, fd).detach();
  }
}
