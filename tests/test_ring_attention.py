"""Ring attention (sequence parallel) vs single-device reference, on the
8-device CPU mesh — the multi-place in-process fixture pattern."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.parallel.ring_attention import ring_attention


def _ref(q, k, v, causal=False):
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sl = s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((sl, sl), jnp.bool_)), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _rand(b=2, h=2, s=64, d=16, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
                 for _ in range(3))


def test_ring_matches_reference():
    mesh = pt.make_mesh({"sp": 8})
    q, k, v = _rand()
    out = ring_attention(q, k, v, mesh, causal=False, batch_axes=())
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)


def test_ring_causal_matches_reference():
    mesh = pt.make_mesh({"sp": 8})
    q, k, v = _rand(seed=1)
    out = ring_attention(q, k, v, mesh, causal=True, batch_axes=())
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v, True)),
                               atol=2e-5, rtol=2e-5)


def test_ring_with_dp_batch_sharding():
    mesh = pt.make_mesh({"dp": 2, "sp": 4})
    q, k, v = _rand(b=4, s=32, seed=2)
    out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v, True)),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_ring_gradients():
    mesh = pt.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    q, k, v = _rand(b=1, h=1, s=32, d=8, seed=3)

    g1 = jax.grad(lambda a: jnp.sum(ring_attention(a, k, v, mesh, causal=True,
                                                   batch_axes=()) ** 2))(q)
    g2 = jax.grad(lambda a: jnp.sum(_ref(a, k, v, True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4, rtol=1e-3)

    gk1 = jax.grad(lambda b_: jnp.sum(ring_attention(q, b_, v, mesh, causal=True,
                                                     batch_axes=()) ** 2))(k)
    gk2 = jax.grad(lambda b_: jnp.sum(_ref(q, b_, v, True) ** 2))(k)
    np.testing.assert_allclose(np.asarray(gk1), np.asarray(gk2), atol=1e-4, rtol=1e-3)


def test_zigzag_causal_matches_reference():
    """Default causal schedule is the balanced zigzag; numerics must be
    identical to dense causal attention."""
    mesh = pt.make_mesh({"sp": 8})
    q, k, v = _rand(seed=6)
    out = ring_attention(q, k, v, mesh, causal=True, batch_axes=(),
                         schedule="zigzag")
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v, True)),
                               atol=2e-5, rtol=2e-5)


def test_zigzag_is_default_for_causal():
    mesh = pt.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    q, k, v = _rand(s=32, seed=7)
    auto = ring_attention(q, k, v, mesh, causal=True, batch_axes=())
    zz = ring_attention(q, k, v, mesh, causal=True, batch_axes=(),
                        schedule="zigzag")
    np.testing.assert_allclose(np.asarray(auto), np.asarray(zz), atol=1e-6)
    np.testing.assert_allclose(np.asarray(auto), np.asarray(_ref(q, k, v, True)),
                               atol=2e-5, rtol=2e-5)


def test_zigzag_with_dp_batch_sharding():
    mesh = pt.make_mesh({"dp": 2, "sp": 4})
    q, k, v = _rand(b=4, s=32, seed=8)
    out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v, True)),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_zigzag_gradients():
    mesh = pt.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    q, k, v = _rand(b=1, h=1, s=32, d=8, seed=9)

    for wrt, arrs in (("q", (q,)), ("k", (k,)), ("v", (v,))):
        def f(a):
            qq, kk, vv = (a if wrt == "q" else q, a if wrt == "k" else k,
                          a if wrt == "v" else v)
            return jnp.sum(ring_attention(qq, kk, vv, mesh, causal=True,
                                          batch_axes=(), schedule="zigzag") ** 2)

        def fr(a):
            qq, kk, vv = (a if wrt == "q" else q, a if wrt == "k" else k,
                          a if wrt == "v" else v)
            return jnp.sum(_ref(qq, kk, vv, True) ** 2)

        g1 = jax.grad(f)(arrs[0])
        g2 = jax.grad(fr)(arrs[0])
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   atol=1e-4, rtol=1e-3, err_msg=f"d{wrt}")


def test_zigzag_persistent_layout():
    """layout='zigzag': caller keeps activations in zigzag order across
    the stack — no per-call gathers; output comes back in zigzag order."""
    from paddle_tpu.parallel.ring_attention import zigzag_order

    mesh = pt.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    q, k, v = _rand(s=32, seed=10)
    order = zigzag_order(32, 4)
    qz, kz, vz = (jnp.take(a, order, axis=2) for a in (q, k, v))
    out_z = ring_attention(qz, kz, vz, mesh, causal=True, batch_axes=(),
                           schedule="zigzag", layout="zigzag")
    ref = jnp.take(_ref(q, k, v, True), order, axis=2)
    np.testing.assert_allclose(np.asarray(out_z), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_bad_schedule_rejected():
    from paddle_tpu.core.errors import EnforceError

    mesh = pt.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    q, k, v = _rand(s=32, seed=11)
    with pytest.raises(EnforceError):
        ring_attention(q, k, v, mesh, causal=True, schedule="zig-zag")
    with pytest.raises(EnforceError):
        ring_attention(q, k, v, mesh, causal=True, layout="weird")


def test_causal_work_balance():
    """The schedule accounting the zigzag exists for: per-rank FLOP
    balance. Plain ring is maximally skewed (last rank 2n-1 x the
    first); zigzag is flat; both do the same total work."""
    from paddle_tpu.parallel.ring_attention import causal_work_per_rank

    for n in (2, 4, 8, 16):
        ring = causal_work_per_rank(n, "ring")
        zz = causal_work_per_rank(n, "zigzag")
        assert sum(ring) == sum(zz) == 2 * n * n
        assert max(zz) == min(zz), "zigzag must be perfectly balanced"
        assert max(ring) / min(ring) == 2 * n - 1


def test_zigzag_order_roundtrip():
    from paddle_tpu.parallel.ring_attention import zigzag_order

    order = np.asarray(zigzag_order(16, 4))
    assert sorted(order.tolist()) == list(range(16))
    # rank r's shard = blocks (r, 2n-1-r) of the 2n-block split
    assert order[:4].tolist() == [0, 1, 14, 15]
    assert order[4:8].tolist() == [2, 3, 12, 13]


def test_degenerate_single_shard():
    mesh = pt.make_mesh({"dp": 8})  # no sp axis
    q, k, v = _rand(s=16, seed=4)
    out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v, True)),
                               atol=2e-5, rtol=2e-5)


def test_ring_inside_jit():
    mesh = pt.make_mesh({"sp": 8})
    q, k, v = _rand(seed=5)

    @jax.jit
    def f(q, k, v):
        return ring_attention(q, k, v, mesh, causal=False, batch_axes=())

    np.testing.assert_allclose(np.asarray(f(q, k, v)), np.asarray(_ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)
