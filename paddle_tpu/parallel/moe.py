"""Mixture-of-experts with expert parallelism over the mesh ``ep`` axis.

Gap-fill component (SURVEY §2.2: TP/PP/SP/**MoE-EP** are absent in the
reference — its only model partitioning is the distributed lookup table,
distribute_transpiler.py:1100). This supplies the modern equivalent:
a top-k-routed expert FFN bank whose experts are sharded across the
``ep`` mesh axis, with token dispatch as ``lax.all_to_all`` pairs riding
ICI — the TPU-native analog of the reference's prefetch-RPC row-sharded
table (split_ids → PrefetchVariable → merge becomes dispatch-einsum →
all_to_all → combine-einsum).

Design (GShard/Switch-style, static shapes for XLA):
- router softmax in f32, top-k selection with a *static capacity* per
  expert: C = ceil(local_tokens · k / E · capacity_factor). Tokens over
  capacity are dropped (their combine weight is zero) — this is what
  keeps every shape static under jit.
- dispatch/combine are one-hot einsums → the MXU does the routing.
- expert compute is a batched einsum over the local expert bank
  ([E_local, C·n, d] @ [E_local, d, ff]) — large, batched, bf16-ready.
- EP path runs under ``shard_map``: experts sharded on ``ep``, tokens
  sharded on (data axes + ``ep``), two tiled all_to_alls swap the
  token↔expert sharding around the expert compute.

Returns ``(out, aux_loss)`` — aux_loss is the load-balance term
(mean-prob · dispatch-fraction · E) to be added to the model loss.
"""

from __future__ import annotations

import contextlib
import functools
import math
import threading
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..framework import LayerHelper, cast_compute
from .. import initializer as init
from . import mesh as mesh_lib


# -- static-config capture (analysis.contracts / moe:capacity lint) ---------
# Every moe() call records its routing shape here when a capture is
# active: the capacity/top_k/token numbers are fully static (they size
# the dispatch tensors), so the expected token drop rate is computable
# without running anything. analysis.check wraps its program traces in
# capture_moe_configs() and feeds the records to rules.check_moe_capacity.

_capture_tls = threading.local()


@contextlib.contextmanager
def capture_moe_configs():
    """Collect the static routing config of every ``moe()`` layer traced
    inside the block. Yields the list the records append to. Nested
    captures each see only their own block's layers; with no capture
    active, recording is a no-op (zero trace-time cost)."""
    prev = getattr(_capture_tls, "log", None)
    _capture_tls.log = log = []
    try:
        yield log
    finally:
        _capture_tls.log = prev


def _record_config(**cfg) -> None:
    log = getattr(_capture_tls, "log", None)
    if log is not None:
        log.append(cfg)


def _topk_dispatch(probs, top_k: int, capacity: int, normalize_gates: bool):
    """Build dispatch/combine tensors [t, E, C] from router probs [t, E].

    Position-in-expert is assigned k-major (all 1st choices before any
    2nd choices), matching GShard's priority so 1st-choice tokens are
    dropped last.
    """
    t, e = probs.shape
    vals, idx = jax.lax.top_k(probs, top_k)            # [t, k]
    if normalize_gates:
        vals = vals / (jnp.sum(vals, axis=-1, keepdims=True) + 1e-9)
    mask = jax.nn.one_hot(idx, e, dtype=jnp.float32)   # [t, k, E]
    flat = jnp.transpose(mask, (1, 0, 2)).reshape(top_k * t, e)
    pos = jnp.cumsum(flat, axis=0) - flat              # position within expert
    pos = jnp.transpose(pos.reshape(top_k, t, e), (1, 0, 2))
    pos_k = jnp.sum(pos * mask, axis=-1)               # [t, k]
    keep = (pos_k < capacity).astype(jnp.float32)
    slot = jax.nn.one_hot(pos_k.astype(jnp.int32), capacity, dtype=jnp.float32)
    dispatch = jnp.einsum("tke,tkc,tk->tec", mask, slot, keep)
    combine = jnp.einsum("tke,tkc,tk->tec", mask, slot, keep * vals)
    return dispatch, combine, mask


def _aux_loss(probs, mask):
    """Load-balance loss (Switch eq. 4): E · Σ_e fraction_e · meanprob_e."""
    e = probs.shape[-1]
    me = jnp.mean(probs, axis=0)                       # mean router prob per expert
    ce = jnp.mean(jnp.sum(mask, axis=1), axis=0)       # fraction routed per expert
    ce = ce / jnp.maximum(jnp.sum(ce), 1e-9)
    return e * jnp.sum(me * ce)


def _expert_ffn(xe, w1, b1, w2, b2, act):
    """Batched expert FFN: xe [E_local, C', d] through per-expert weights.

    Plain compute-dtype einsums (no f32 preferred_element_type): XLA's
    TPU matmul accumulates bf16 in f32 regardless, and an f32-output
    einsum over bf16 operands makes autodiff compute the backward dots
    as f32×f32 — the ~1/8-rate MXU path (same trap the attention
    scores custom-VJP fixes)."""
    xe, w1, w2 = cast_compute(xe, w1, w2)
    h = jnp.einsum("ecd,edf->ecf", xe, w1) + b1[:, None, :].astype(xe.dtype)
    h = act(h)
    y = jnp.einsum("ecf,efd->ecd", h, w2) + b2[:, None, :].astype(xe.dtype)
    return y


def _route_compute(xt, wg, w1, b1, w2, b2, *, top_k, capacity, act,
                   normalize_gates, exchange=None):
    """Shared router→dispatch→experts→combine over tokens [t, d].
    ``exchange(x, inverse)`` wraps the expert compute with the EP
    token↔expert reshard; None on the dense path."""
    # router stays f32 (gate correctness); everything sized by tokens —
    # dispatch/combine one-hot einsums and the expert bank — runs in the
    # compute dtype (the dispatch einsum's t·E·C·d flops rival the
    # expert FFN's at real capacity factors)
    logits = jnp.matmul(xt.astype(jnp.float32), wg)
    probs = jax.nn.softmax(logits, axis=-1)
    dispatch, combine, mask = _topk_dispatch(probs, top_k, capacity, normalize_gates)
    aux = _aux_loss(probs, mask)
    xt_c = cast_compute(xt)
    xe = jnp.einsum("tec,td->ecd", dispatch.astype(xt_c.dtype), xt_c)  # [E, C, d]
    if exchange is not None:
        xe = exchange(xe, inverse=False)
    ye = _expert_ffn(xe, w1, b1, w2, b2, act)
    if exchange is not None:
        ye = exchange(ye, inverse=True)
    yt = jnp.einsum("tec,ecd->td", combine.astype(ye.dtype), ye)
    return yt, aux


def _moe_body(x, wg, w1, b1, w2, b2, *, axis_name, top_k, capacity, act,
              normalize_gates, data_axes):
    """Per-device EP computation: x [b_local, s, d] local tokens,
    w1/b1/w2/b2 local expert shard [E_local, ...], wg replicated."""
    varying = tuple(data_axes) + (axis_name,)
    wg = mesh_lib.pvary(wg, varying)
    if data_axes:
        w1, b1, w2, b2 = (mesh_lib.pvary(a, tuple(data_axes)) for a in (w1, b1, w2, b2))

    def exchange(x, inverse):
        # token-shard ↔ expert-shard: [E, C, d] → [E/n, n·C, d] and back
        split, concat = (1, 0) if inverse else (0, 1)
        return jax.lax.all_to_all(x, axis_name, split_axis=split,
                                  concat_axis=concat, tiled=True)

    b, s, d = x.shape
    yt, aux = _route_compute(x.reshape(b * s, d), wg, w1, b1, w2, b2,
                             top_k=top_k, capacity=capacity, act=act,
                             normalize_gates=normalize_gates, exchange=exchange)
    aux = jax.lax.pmean(aux, varying)
    return yt.reshape(b, s, d).astype(x.dtype), aux


def moe(
    x,
    num_experts: int,
    d_ff: int,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    mesh: Optional[Mesh] = None,
    axis_name: str = mesh_lib.EP,
    act: str = "gelu",
    normalize_gates: bool = True,
    param_attr=None,
    name: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k-routed MoE FFN over ``x`` [batch, seq, d_model].

    Returns ``(out, aux_loss)``. With ``mesh`` given and its ``ep`` axis
    >1, experts are sharded over ``ep`` and tokens dispatched via
    all_to_all (batch must be sharded over data axes + ``ep``);
    otherwise runs the dense single-device path with identical numerics
    (capacity permitting).
    """
    from ..layers.ops import apply_activation

    helper = LayerHelper("moe", name=name)
    b, s, d = x.shape
    act_fn = lambda h: apply_activation(h, act)

    wg = helper.create_parameter("router_w", shape=(d, num_experts),
                                 dtype=jnp.float32, attr=param_attr)
    w1 = helper.create_parameter("expert_w1", shape=(num_experts, d, d_ff),
                                 dtype=jnp.float32, attr=param_attr)
    b1 = helper.create_parameter("expert_b1", shape=(num_experts, d_ff),
                                 dtype=jnp.float32, initializer=init.Constant(0.0))
    w2 = helper.create_parameter("expert_w2", shape=(num_experts, d_ff, d),
                                 dtype=jnp.float32, attr=param_attr)
    b2 = helper.create_parameter("expert_b2", shape=(num_experts, d),
                                 dtype=jnp.float32, initializer=init.Constant(0.0))

    ep = mesh.shape[axis_name] if mesh is not None and axis_name in mesh.axis_names else 1
    if ep > 1 and num_experts % ep != 0:
        raise ValueError(f"num_experts={num_experts} not divisible by ep={ep}")

    data_axes = tuple(a for a in (mesh_lib.DATA_AXES if mesh is None else
                                  mesh_lib.data_axis_names(mesh))
                      if mesh is not None and mesh.shape[a] > 1)
    shards = ep * int(np.prod([mesh.shape[a] for a in data_axes] or [1]))
    t_local = (b // max(1, shards)) * s if ep > 1 else b * s
    capacity = max(1, int(math.ceil(t_local * top_k / num_experts * capacity_factor)))
    # record under the FULL scoped path (what params are named under):
    # two MoE layers in different scopes are distinct findings — the
    # scope-local helper name ("moe_0") would collide their fingerprints
    # and a baseline for one would suppress the other
    from ..framework import current_context
    _ctx = current_context()
    _record_config(name=_ctx.full_name(helper.name) if _ctx else helper.name,
                   num_experts=num_experts, top_k=top_k,
                   capacity_factor=float(capacity_factor), capacity=capacity,
                   tokens=t_local, ep=ep)

    if ep == 1:
        # dense path (single device / ep absent): same algorithm, no collectives
        yt, aux = _route_compute(x.reshape(b * s, d), wg, w1, b1, w2, b2,
                                 top_k=top_k, capacity=capacity, act=act_fn,
                                 normalize_gates=normalize_gates)
        return yt.reshape(b, s, d).astype(x.dtype), aux

    batch_shard = tuple(data_axes) + (axis_name,)
    xspec = P(batch_shard if len(batch_shard) > 1 else batch_shard[0], None, None)
    espec = P(axis_name)
    body = functools.partial(_moe_body, axis_name=axis_name, top_k=top_k,
                             capacity=capacity, act=act_fn,
                             normalize_gates=normalize_gates, data_axes=data_axes)
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(xspec, P(), espec, espec, espec, espec),
        out_specs=(xspec, P()))
    return fn(x, wg, w1, b1, w2, b2)


def moe_ep_rules():
    """Sharding-rule entries placing expert banks on ``ep`` — append to a
    ShardingRules table (transformer_tp_rules(extra=moe_ep_rules()))."""
    return [
        (r".*moe.*/expert_(w1|b1|w2|b2)$", P("ep")),
        (r".*moe.*/router_w$", P()),
    ]
