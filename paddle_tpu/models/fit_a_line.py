"""fit_a_line — the first book chapter (tests/book/test_fit_a_line.py):
linear regression on UCI housing (13 features → price) with square
error cost. The smallest end-to-end program in the reference; kept as
the minimal smoke model here too."""

from __future__ import annotations

from .. import layers


def make_model():
    def fit_a_line(x, y):
        """x: [b, 13] float features; y: [b, 1] float prices."""
        y_predict = layers.fc(x, 1, name="fc")
        cost = layers.square_error_cost(y_predict, y)
        avg_cost = layers.mean(cost)
        return {"loss": avg_cost, "pred": y_predict}

    return fit_a_line
