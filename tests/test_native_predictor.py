"""Python-free native predictor (native/predictor.cc) — the C++
inference entry parity test (inference/io.h:35, api_impl.cc:64).

The binary speaks the PJRT C API directly: it dlopens a plugin
(libtpu.so on TPU hosts), compiles the exported StableHLO, stages
weights/feeds as device buffers, executes, and prints checksums — no
libpython anywhere in the process.

On this CI box the TPU is only reachable through an IFRT-proxy tunnel
(not a PJRT C API endpoint), so the full execute path needs real local
hardware. What IS asserted hermetically:
  * the binary builds against the vendored PJRT C API header,
  * --probe exits 0: plugin dlopen + GetPjrtApi version handshake + the
    complete Python-free artifact load (zip64 npz weights, meta.json
    signature, StableHLO bytes) with shape/dtype/size cross-validation,
  * artifact tampering is caught loudly,
  * when a local device IS present, the full run's f32 output checksum
    matches the Python Predictor.
"""

import os
import subprocess

import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu import layers as L

TF_INCLUDE = "/opt/venv/lib/python3.12/site-packages/tensorflow/include"
LIBTPU = "/opt/venv/lib/python3.12/site-packages/libtpu/libtpu.so"

pytestmark = pytest.mark.skipif(
    not os.path.exists(os.path.join(TF_INCLUDE, "xla/pjrt/c/pjrt_c_api.h"))
    or not os.path.exists(LIBTPU),
    reason="PJRT C API header or libtpu plugin not present in this image")


def _build():
    from paddle_tpu.native import build_native
    return build_native("predictor.cc", "predictor",
                        extra_flags=("-I" + TF_INCLUDE,), libs=("-ldl",))


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("pred"))

    def net(x):
        h = L.fc(x, 8, act="relu", name="h")
        return {"y": L.fc(h, 3, name="out")}

    x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
    prog = pt.build(net)
    params, state = prog.init(jax.random.PRNGKey(0), x=x)
    pio.save_inference_model(d, prog, params, state, {"x": x})
    np.save(os.path.join(d, "feed_x.npy"), x)
    pred = pio.load_inference_model(d)
    out = pred.run({"x": x})
    ref = np.asarray(out["y"] if isinstance(out, dict) else out)
    return d, float(ref.astype(np.float64).sum())


@pytest.mark.slow
def test_probe_python_free(artifact):
    d, _ = artifact
    binpath = _build()
    r = subprocess.run([binpath, d, LIBTPU, "--probe"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "PROBE OK" in r.stdout
    assert "artifact ok" in r.stderr          # weights+signature validated
    assert "PJRT API v" in r.stderr           # plugin handshake happened
    # no python in the process: sanity — the binary links no libpython
    ldd = subprocess.run(["ldd", binpath], capture_output=True, text=True)
    assert "libpython" not in ldd.stdout


@pytest.mark.slow
def test_tampered_artifact_rejected(artifact, tmp_path):
    import shutil
    d, _ = artifact
    bad = tmp_path / "bad"
    shutil.copytree(d, bad)
    meta = (bad / "meta.json").read_text()
    # corrupt a weight shape in the signature: 8 -> 80
    (bad / "meta.json").write_text(meta.replace('"shape": [4, 8]',
                                                '"shape": [4, 80]', 1))
    binpath = _build()
    r = subprocess.run([binpath, str(bad), LIBTPU, "--probe"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode != 0
    assert "signature expects" in r.stderr


@pytest.mark.slow
def test_full_run_on_local_device_if_present(artifact):
    """Full PJRT execute — needs a device the plugin can open locally.
    On tunnel-only boxes assert the failure is the device probe, i.e.
    everything before hardware (artifact, handshake, compile options)
    held up."""
    d, ref_sum = artifact
    binpath = _build()
    r = subprocess.run([binpath, d, LIBTPU], capture_output=True, text=True,
                       timeout=600)
    if r.returncode == 0:
        assert "RUN OK" in r.stdout
        line = [l for l in r.stdout.splitlines() if l.startswith("OUTPUT 0")][0]
        got = float(line.split("f32sum=")[1])
        np.testing.assert_allclose(got, ref_sum, rtol=1e-3)
    else:
        assert "client create" in r.stderr, r.stderr
        pytest.skip("no local PJRT device (TPU is tunnel-only on this box): "
                    + r.stderr.strip().splitlines()[-1][:120])
