"""Composite nets — python/paddle/fluid/nets.py analog
(simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention)."""

from __future__ import annotations

import jax.numpy as jnp

from . import layers as L
from .layers import attention as A


def simple_img_conv_pool(input, num_filters, filter_size, pool_size, pool_stride,
                         pool_padding=0, pool_type="max", act=None,
                         conv_stride=1, conv_padding=0, conv_dilation=1,
                         conv_groups=1, param_attr=None, bias_attr=None):
    conv = L.conv2d(input, num_filters, filter_size, stride=conv_stride,
                    padding=conv_padding, dilation=conv_dilation,
                    groups=conv_groups, param_attr=param_attr,
                    bias_attr=bias_attr, act=act)
    return L.pool2d(conv, pool_size=pool_size, pool_type=pool_type,
                    pool_stride=pool_stride, pool_padding=pool_padding)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act="relu", conv_with_batchnorm=False,
                   conv_batchnorm_drop_rate=0.0, pool_stride=1, pool_type="max"):
    tmp = input
    for i, nf in enumerate(conv_num_filter):
        tmp = L.conv2d(tmp, nf, conv_filter_size, padding=conv_padding,
                       act=None if conv_with_batchnorm else conv_act)
        if conv_with_batchnorm:
            tmp = L.batch_norm(tmp, act=conv_act)
            if conv_batchnorm_drop_rate:
                tmp = L.dropout(tmp, conv_batchnorm_drop_rate)
    return L.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                    pool_stride=pool_stride)


def sequence_conv_pool(input, lengths, num_filters, filter_size, act="tanh",
                       pool_type="max"):
    """Conv over time on a padded batch [b, t, d] + masked pool —
    sequence_conv_pool analog for the padded representation."""
    b, t, d = input.shape
    x = jnp.transpose(input, (0, 2, 1))[:, :, None, :]  # [b, d, 1, t]
    conv = L.conv2d(x, num_filters, (1, filter_size),
                    padding=(0, (filter_size - 1) // 2), act=act)
    conv = jnp.transpose(conv[:, :, 0, :], (0, 2, 1))  # [b, t, nf]
    mask = (jnp.arange(t)[None, :] < lengths[:, None])
    if pool_type == "max":
        conv = jnp.where(mask[..., None], conv, -jnp.inf)
        return conv.max(axis=1)
    conv = jnp.where(mask[..., None], conv, 0.0)
    return conv.sum(axis=1) / jnp.maximum(mask.sum(axis=1, keepdims=True), 1).astype(conv.dtype)


def glu(input, dim=-1):
    a, b = L.split(input, 2, dim=dim)
    return a * L.sigmoid(b)


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """nets.scaled_dot_product_attention analog over [b, s, d] inputs."""
    b, sq, d = queries.shape
    hd = d // num_heads

    def split_heads(x):
        return x.reshape(x.shape[0], x.shape[1], num_heads, hd).transpose(0, 2, 1, 3)

    out = A.scaled_dot_product_attention(
        split_heads(queries), split_heads(keys), split_heads(values),
        dropout_rate=dropout_rate)
    return out.transpose(0, 2, 1, 3).reshape(b, sq, d)
