"""ZeRO-style cross-replica sharded weight update
(``DistStrategy(zero_sharding=True)``): params + optimizer state live
as per-replica 1/N shard rows, gradients reduce-scatter, the update
applies shard-locally, and fresh params all-gather at the top of every
(fused) step.

Pinned here:
- train equivalence vs the replicated update (SGD / Momentum / amp
  dynamic loss scaling) — allclose, NOT bitwise: the exchange program's
  reduce order changes, so exact equality is the wrong contract;
- the bitwise pins that DO hold: fused-K dispatch == K sequential
  steps with the sharded carry donated end-to-end, and
  ``zero_sharding=False`` == no strategy at all (today's path,
  bit-identical);
- composition with ``quantized_allreduce="int8"`` (the error-feedback
  residuals stay shard-local) and the ``collective`` line's
  ``zero`` attribution (all-gather bytes/step);
- shard-aware checkpoints: per-shard ``*.zero{i}.npz`` files, manifest
  + ``meta.zero`` coverage, same-N restore shard-local and bit-exact,
  zero<->replicated restores gated as structured ``ReshardError``,
  N→M via explicit gather-then-repartition (``reshard_restore``);
- the elastic acceptance drill: SIGTERM kills a dp=4 ZeRO run, the job
  rejoins at dp=2 with ``fit(resume=True, elastic=True)``, and the
  resumed tail matches a bare-step continuation bit-for-bit;
- torn/stray shard files: ``restore_latest`` treats a damaged shard
  set as corrupt AS A UNIT (falls back to the previous checkpoint, no
  Frankenstein mix);
- the lint flip (``sharding:replicated-optstate`` quiet under ZeRO,
  ``sharding:zero-active`` info with realized per-device bytes), the
  ``ckpt:zero-mismatch`` finding, the advisor/device-cache HBM
  dividend, and the bench row schema.
"""

import os
import signal

import numpy as np
import pytest

import jax

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu import layers as L
from paddle_tpu import optimizer as opt
from paddle_tpu import resilience
from paddle_tpu.data.feeder import DataFeeder
from paddle_tpu.parallel import DistStrategy
from paddle_tpu.testing import faults

DIM, CLASSES, BS, N_BATCHES = 6, 4, 8, 8


def _net(x, label):
    h = L.fc(x, 16, name="fc1")
    logits = L.fc(h, CLASSES, name="fc2")
    return {"loss": L.mean(L.softmax_with_cross_entropy(logits, label))}


_FEED = {"x": np.random.RandomState(3).randn(BS, DIM).astype(np.float32),
         "label": np.random.RandomState(4).randint(
             0, CLASSES, (BS, 1)).astype(np.int64)}

ZERO = DistStrategy(zero_sharding=True)


def _mesh(n):
    return (pt.make_mesh({"dp": n}, devices=jax.devices()[:n])
            if n > 1 else None)


def _trainer(n=4, strategy=ZERO, optim=None, **kw):
    tr = pt.Trainer(pt.build(_net), optim or opt.SGD(0.1),
                    loss_name="loss", mesh=_mesh(n), strategy=strategy, **kw)
    tr.startup(sample_feed=_FEED)
    return tr


def _feeds(k, seed=11):
    rng = np.random.RandomState(seed)
    return [{"x": rng.randn(BS, DIM).astype(np.float32),
             "label": rng.randint(0, CLASSES, (BS, 1)).astype(np.int64)}
            for _ in range(k)]


def _run(tr, feeds):
    return [float(tr.step(f)["loss"]) for f in feeds]


def _params_equal(a, b):
    a, b = jax.device_get(a), jax.device_get(b)
    return set(a) == set(b) and all(np.array_equal(a[k], b[k]) for k in a)


def _flat_equal(tree_a, tree_b):
    fa = pio._flatten(jax.device_get(tree_a))
    fb = pio._flatten(jax.device_get(tree_b))
    return set(fa) == set(fb) and all(np.array_equal(fa[k], fb[k])
                                      for k in fa)


def _logical(tr):
    return jax.device_get(tr._logical_params())


def _reader(n_batches=N_BATCHES, seed=7):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n_batches):
            x = rng.randn(BS, DIM).astype(np.float32)
            y = rng.randint(0, CLASSES, (BS,)).astype(np.int64)
            yield [(x[j], y[j:j + 1]) for j in range(BS)]
    return reader


def _fit(tr, cfg=None, epochs=2, handler=None, **kw):
    return pt.fit(tr, _reader(), num_epochs=epochs,
                  feed_names=["x", "label"], dtypes=["float32", "int64"],
                  checkpoint_config=cfg, event_handler=handler, **kw)


def _manual_continue(tr, meta, epochs=2, n_batches=N_BATCHES):
    feeder = DataFeeder(["x", "label"], ["float32", "int64"])
    losses = []
    for epoch in range(int(meta.get("epoch", 0)), epochs):
        skip = int(meta.get("epoch_step", 0)) \
            if epoch == int(meta.get("epoch", 0)) else 0
        for i, samples in enumerate(_reader(n_batches)()):
            if i < skip:
                continue
            losses.append(float(tr.step(feeder.feed(samples))["loss"]))
    return losses


# -- train equivalence vs the replicated update ------------------------------


@pytest.mark.parametrize("optim", [lambda: opt.SGD(0.1),
                                   lambda: opt.Momentum(0.05, 0.9)],
                         ids=["sgd", "momentum"])
def test_train_equivalence_vs_replicated(optim):
    """6 steps at dp=4: the sharded update tracks the replicated one to
    float tolerance (the exchange reduce order changes, so bitwise is
    not the contract) and the shard trees really are 1/N rows."""
    feeds = _feeds(6)
    rep = _trainer(4, strategy=None, optim=optim())
    zer = _trainer(4, strategy=ZERO, optim=optim())
    assert zer._zero is not None and zer._zero.n == 4
    for name, leaf in zer.scope.params.items():
        assert leaf.ndim == 2 and leaf.shape[0] == 4, (name, leaf.shape)
    rl, zl = _run(rep, feeds), _run(zer, feeds)
    np.testing.assert_allclose(zl, rl, rtol=1e-5, atol=1e-7)
    want, got = jax.device_get(rep.scope.params), _logical(zer)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5, atol=1e-7)


def test_amp_dynamic_loss_scale_composes():
    """ZeRO + amp dynamic loss scaling: losses track the replicated amp
    run and the scaler state stays identical (unscale happens before
    the reduce-scatter, so overflow accounting must not diverge)."""
    amp = dict(loss_scale=2.0 ** 10, dynamic_loss_scale=True)
    feeds = _feeds(5)
    rep = _trainer(4, strategy=DistStrategy(**amp))
    zer = _trainer(4, strategy=DistStrategy(zero_sharding=True, **amp))
    rl, zl = _run(rep, feeds), _run(zer, feeds)
    np.testing.assert_allclose(zl, rl, rtol=1e-5, atol=1e-7)
    ls_rep = jax.device_get(rep.scope.loss_scale_state)
    ls_zer = jax.device_get(zer.scope.loss_scale_state)
    assert {k: float(v) for k, v in ls_rep.items()} \
        == {k: float(v) for k, v in ls_zer.items()}


def test_fused_k_equals_sequential_bitwise():
    """run_steps(K=6) on the sharded carry == 6 sequential step() calls
    BITWISE — loss stream, shard params, and opt state (the fused scan
    must thread the exact same shard trees it donates)."""
    feeds = _feeds(6, seed=13)
    seq = _trainer(4)
    fused = _trainer(4)
    seq_losses = _run(seq, feeds)
    stacked = {k: np.stack([f[k] for f in feeds]) for k in feeds[0]}
    out = fused.run_steps(stacked, k=6)
    fused_losses = np.asarray(out["loss"]).reshape(-1).tolist()
    assert fused_losses == seq_losses
    assert _params_equal(seq.scope.params, fused.scope.params)
    assert _flat_equal(seq.scope.opt_state, fused.scope.opt_state)


def test_zero_off_is_bitwise_noop():
    """zero_sharding=False is today's path bit-for-bit: same losses,
    same params as a strategy-less trainer, and no ZeroSpec is built."""
    feeds = _feeds(4)
    base = _trainer(4, strategy=None)
    off = _trainer(4, strategy=DistStrategy(zero_sharding=False))
    assert off._zero is None
    assert _run(base, feeds) == _run(off, feeds)
    assert _params_equal(base.scope.params, off.scope.params)


def test_quantized_allreduce_int8_composes():
    """ZeRO + int8 quantized exchange: the error-feedback residuals
    live shard-local on the data axis (never replicated back), training
    stays finite and tracks fp32-exchange ZeRO loosely, and the
    collective line carries both attributions."""
    feeds = _feeds(6)
    q = DistStrategy(zero_sharding=True, quantized_allreduce="int8")
    zq = _trainer(4, strategy=q)
    losses = _run(zq, feeds)
    assert np.all(np.isfinite(losses))
    resid = zq.scope.quant_resid
    assert resid, "error-feedback residuals missing"
    for name, leaf in resid.items():
        spec = tuple(leaf.sharding.spec)
        assert spec and spec[0] == "dp", (name, spec)
    coll = zq.collective_bytes
    assert coll["zero"]["shards"] == 4
    assert coll["zero"]["allgather_bytes_per_step"] > 0


# -- shard-aware checkpoints -------------------------------------------------


def test_save_restore_same_n_bitwise(tmp_path):
    """Save at dp=4 after 3 Momentum steps, restore into a fresh dp=4
    ZeRO trainer: shard-local (per-shard row files, no gather), params
    AND opt state bit-exact, manifest covers every shard file, and the
    next step out of each trainer is bitwise identical."""
    feeds = _feeds(4)
    src = _trainer(4, optim=opt.Momentum(0.1, 0.9))
    _run(src, feeds[:3])
    ck = str(tmp_path / "ck")
    pio.save_trainer(ck, src)

    names = sorted(os.listdir(ck))
    assert [f"params.zero{i}.npz" for i in range(4)] == \
        [n for n in names if n.startswith("params.zero")]
    assert [f"opt_state.zero{i}.npz" for i in range(4)] == \
        [n for n in names if n.startswith("opt_state.zero")]
    man = resilience.read_manifest(ck)
    assert man["meta"]["zero_axes"] == {"dp": 4}
    assert man["meta"]["zero"]["shards"] == 4
    for i in range(4):
        assert f"params.zero{i}.npz" in man["files"]

    tgt = _trainer(4, optim=opt.Momentum(0.1, 0.9))
    pio.load_trainer(ck, tgt)
    assert tgt.global_step == src.global_step
    assert _params_equal(src.scope.params, tgt.scope.params)
    assert _flat_equal(src.scope.opt_state, tgt.scope.opt_state)
    a = float(src.step(feeds[3])["loss"])
    b = float(tgt.step(feeds[3])["loss"])
    assert a == b
    assert _params_equal(src.scope.params, tgt.scope.params)


def test_zero_layout_change_is_gated_then_reshardable(tmp_path):
    """zero<->replicated (and zero N→M) restores are structured
    ReshardErrors on the plain path, and reshard_restore performs the
    explicit gather-then-repartition with bytes reported — landing
    bit-exact against the saved logical state."""
    src = _trainer(4, optim=opt.Momentum(0.1, 0.9))
    _run(src, _feeds(3))
    logical_before = _logical(src)
    ck = str(tmp_path / "ck")
    pio.save_trainer(ck, src)

    with pytest.raises(resilience.ReshardError, match="zero_sharding"):
        pio.load_trainer(ck, _trainer(4, strategy=None,
                                      optim=opt.Momentum(0.1, 0.9)))
    rep_ck = str(tmp_path / "rep")
    rep_src = _trainer(4, strategy=None, optim=opt.Momentum(0.1, 0.9))
    pio.save_trainer(rep_ck, rep_src)
    with pytest.raises(resilience.ReshardError, match="zero_sharding"):
        pio.load_trainer(rep_ck, _trainer(4, optim=opt.Momentum(0.1, 0.9)))

    # dp 4 -> 2 with ZeRO on both sides: explicit fallback door
    tgt = _trainer(2, optim=opt.Momentum(0.1, 0.9))
    rep = resilience.reshard_restore(ck, tgt, sample_feed=_FEED)
    assert rep["bytes_moved"] > 0
    assert tgt._zero is not None and tgt._zero.n == 2
    got = _logical(tgt)
    assert set(got) == set(logical_before)
    for k in got:
        np.testing.assert_array_equal(got[k], logical_before[k])
    assert np.isfinite(float(tgt.step(_FEED)["loss"]))


def test_elastic_fit_kill_and_rejoin_zero(tmp_path):
    """Acceptance drill with ZeRO on: SIGTERM kills a dp=4 sharded run
    at step 5 (boundary checkpoint writes SHARD manifests), the job
    rejoins at dp=2 with fit(resume=True, elastic=True), and the
    resumed tail matches a bare-step dp=2 continuation bit-for-bit."""
    mesh4, mesh2 = faults.membership_meshes([4, 2])
    cfg = pt.CheckpointConfig(str(tmp_path), epoch_interval=0,
                              step_interval=0, max_num_checkpoints=3)

    def kill5(e):
        if e.kind == "end_step" and e.step == 5:
            os.kill(os.getpid(), signal.SIGTERM)

    killed = _fit(_trainer(4), cfg, handler=kill5)
    assert killed.global_step == 5
    ck = str(tmp_path / "step_5")
    man = resilience.read_manifest(ck)
    assert man["meta"]["zero"]["shards"] == 4
    assert any(n.startswith("params.zero") for n in man["files"])

    losses = []
    rejoined = _fit(_trainer(2), cfg, resume=True, elastic=True,
                    handler=lambda e: losses.append(float(e.metrics["loss"]))
                    if e.kind == "end_step" else None)
    assert rejoined.global_step == 2 * N_BATCHES
    assert rejoined._zero is not None and rejoined._zero.n == 2

    ref = _trainer(2)
    rep = resilience.reshard_restore(ck, ref, sample_feed=_FEED)
    ref_losses = _manual_continue(ref, rep["meta"])
    assert losses == ref_losses
    assert _params_equal(rejoined.scope.params, ref.scope.params)


def test_torn_shard_falls_back_as_unit(tmp_path):
    """One flipped byte in ONE shard file of the newest checkpoint
    condemns the whole checkpoint: restore_latest falls back to the
    previous intact one — never a Frankenstein mix of generations."""
    src = _trainer(4)
    src.step(_FEED)
    src.global_step = 2
    pio.save_trainer(str(tmp_path / "step_2"), src,
                     extra_meta={"epoch": 0, "epoch_step": 2})
    src.step(_FEED)
    src.global_step = 4
    pio.save_trainer(str(tmp_path / "step_4"), src,
                     extra_meta={"epoch": 0, "epoch_step": 4})
    faults.flip_byte(str(tmp_path / "step_4"), name="params.zero1.npz")
    with pytest.raises(resilience.CheckpointCorrupt):
        resilience.validate_checkpoint(str(tmp_path / "step_4"))

    tgt = _trainer(4)
    meta = resilience.restore_latest(str(tmp_path), tgt)
    assert meta is not None and tgt.global_step == 2


def test_stray_shard_file_is_corrupt(tmp_path):
    """A shard file on disk that the manifest does not cover (a mix of
    two checkpoint generations) fails validation as a unit."""
    src = _trainer(4)
    ck = str(tmp_path / "ck")
    pio.save_trainer(ck, src)
    with open(os.path.join(ck, "params.zero9.npz"), "wb") as f:
        f.write(b"stray")
    with pytest.raises(resilience.CheckpointCorrupt, match="manifest"):
        resilience.validate_checkpoint(ck)


# -- lint flip, contracts, advisor dividend ----------------------------------


def test_lint_replicated_optstate_flips_to_zero_active():
    """The sharding:replicated-optstate warning goes quiet under ZeRO;
    the companion sharding:zero-active info reports the realized
    per-device opt-state bytes (1/N of the replicated figure)."""
    from paddle_tpu.analysis.contracts import check_artifacts

    rep = _trainer(8, strategy=None, optim=opt.Momentum(0.1, 0.9))
    r1 = check_artifacts(trainer=rep, sample_feed=_FEED,
                         replicated_optstate_bytes=1)
    assert r1.by_code("sharding:replicated-optstate")
    assert not r1.by_code("sharding:zero-active")

    zer = _trainer(8, optim=opt.Momentum(0.1, 0.9))
    r2 = check_artifacts(trainer=zer, sample_feed=_FEED,
                         replicated_optstate_bytes=1)
    assert not r2.by_code("sharding:replicated-optstate")
    info = r2.by_code("sharding:zero-active")
    assert info and info[0].severity == "info"
    assert info[0].data["data_shards"] == 8
    rep_bytes = sum(
        int(np.prod(v.shape or (1,))) * np.dtype(v.dtype).itemsize
        for v in jax.tree.leaves(rep.scope.opt_state))
    assert info[0].data["opt_state_bytes_per_device"] < rep_bytes


def test_check_artifacts_zero_mismatch_finding(tmp_path):
    """check_artifacts understands shard-aware manifests: a ZeRO
    checkpoint against a non-ZeRO trainer (and vice versa) is a
    structured ckpt:zero-mismatch WARNING — while the matching pair
    compares logical-vs-logical specs with no drift noise."""
    from paddle_tpu.analysis.contracts import check_artifacts

    zer = _trainer(4)
    rep = _trainer(4, strategy=None)
    ck = str(tmp_path / "ck")
    pio.save_trainer(ck, zer)

    r = check_artifacts(trainer=rep, checkpoint_dir=ck, sample_feed=_FEED)
    zm = r.by_code("ckpt:zero-mismatch")
    assert zm and zm[0].severity == "warning"
    assert zm[0].data["got"] == {"dp": 4}
    noise = ("ckpt:missing-entry", "ckpt:extra-entry", "ckpt:shape-drift",
             "ckpt:missing-collection")
    assert not any(r.by_code(c) for c in noise), r.render()

    r2 = check_artifacts(trainer=zer, checkpoint_dir=ck, sample_feed=_FEED)
    assert not r2.by_code("ckpt:zero-mismatch"), r2.render()
    assert not any(r2.by_code(c) for c in noise), r2.render()

    rep_ck = str(tmp_path / "rep")
    pio.save_trainer(rep_ck, rep)
    r3 = check_artifacts(trainer=zer, checkpoint_dir=rep_ck,
                         sample_feed=_FEED)
    assert r3.by_code("ckpt:zero-mismatch")


def test_advisor_dividend_and_device_cache_admits_more():
    """memory_estimate divides opt-state (and param) bytes by the data
    shard count under ZeRO (>= 6x at dp=8 — the acceptance number), so
    residual_hbm_bytes grows and a budget that admitted a partial
    prefix replicated admits STRICTLY MORE chunks sharded."""
    from paddle_tpu.data.device_cache import (DeviceCache,
                                              residual_hbm_bytes)
    from paddle_tpu.profiling.advisor import memory_estimate

    rep = _trainer(8, strategy=None, optim=opt.Momentum(0.1, 0.9))
    zer = _trainer(8, optim=opt.Momentum(0.1, 0.9))
    est_rep = memory_estimate(rep, _FEED, project_remat=False)
    est_zer = memory_estimate(zer, _FEED, project_remat=False)
    assert est_rep["opt_state_bytes"] >= 6 * est_zer["opt_state_bytes"]
    assert est_rep["param_bytes"] >= 6 * est_zer["param_bytes"]
    assert est_zer["opt_state_bytes_logical"] \
        == est_rep["opt_state_bytes_logical"]

    # fixed total budget, chunk-sized offers: the ZeRO trainer's larger
    # residual admits a strictly longer (still partial) prefix
    chunk = {"x": jax.device_put(np.zeros((4, BS, DIM), np.float32)),
             "label": jax.device_put(np.zeros((4, BS, 1), np.int64))}
    from paddle_tpu.data.device_cache import device_feed_resident_nbytes
    chunk_b = device_feed_resident_nbytes(chunk)
    budget = int(est_rep["est_total_bytes"] / 0.8) + 2 * chunk_b

    def admitted(tr):
        res = residual_hbm_bytes(tr, _FEED, hbm_budget_bytes=budget)
        cache = DeviceCache(budget_bytes=res)
        n = 0
        while cache.offer(4, chunk):
            n += 1
            if n > 64:
                break
        return n

    n_rep, n_zer = admitted(rep), admitted(zer)
    assert 0 < n_rep < n_zer, (n_rep, n_zer)


def test_bench_zero_sharding_row_schema():
    """The zero_sharding suite row: headline value is the per-device
    optimizer-HBM reduction at the largest dp, per-dp sub-rows carry
    both step times and the all-gather bytes attribution."""
    import bench

    row = bench.bench_zero_sharding(1.0, batch_size=16, iters=2, k=2)
    assert row["value"] >= 6.0
    assert "dp8_opt_hbm_reduction_x" in row
    assert row["dp8_opt_hbm_reduction_x"] >= 6.0
    assert row["dp2_allgather_bytes_per_step"] > 0
    assert row["steps_per_dispatch"] == 2
    for key in ("dp2_step_time_ms_k1_replicated", "dp2_step_time_ms_k1_zero",
                "dp2_step_time_ms_k2_replicated", "dp2_step_time_ms_k2_zero",
                "dp8_step_time_ratio_fused"):
        assert key in row, key
