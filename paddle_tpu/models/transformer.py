"""Transformer (encoder-decoder, WMT en-de "base" config).

Capability analog of the reference's fluid transformer benchmark
(benchmark/fluid/models/machine_translation.py builds attention from
primitive ops; fluid has no attention kernels — SURVEY §5). Re-designed
TPU-first: pre-LN residual blocks, bf16-friendly, parameter names
aligned with parallel.transformer_tp_rules for TP/FSDP sharding, flash
attention switchable.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from .. import layers as L
from ..framework import LayerHelper, name_scope
from ..layers import attention as A
from .. import initializer as init


@dataclasses.dataclass
class TransformerConfig:
    src_vocab: int = 32000
    trg_vocab: int = 32000
    max_len: int = 256
    d_model: int = 512
    d_inner: int = 2048
    num_heads: int = 8
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    dropout: float = 0.1
    label_smooth_eps: float = 0.1
    use_flash: bool = False
    dtype: str = "float32"


def base_config(**kw) -> TransformerConfig:
    return TransformerConfig(**kw)


def _embed(ids, vocab, d_model, dtype, scope_name):
    with name_scope(scope_name):
        emb = L.embedding(ids, size=[vocab, d_model], dtype=dtype,
                          param_attr=None)
    return emb * (d_model ** 0.5)


def encoder_layer(x, cfg: TransformerConfig, mask):
    h = L.layer_norm(x, begin_norm_axis=2)
    h = A.multi_head_attention(h, num_heads=cfg.num_heads, attn_mask=mask,
                               dropout_rate=cfg.dropout, use_flash=cfg.use_flash)
    x = x + L.dropout(h, cfg.dropout, dropout_implementation="upscale_in_train")
    h = L.layer_norm(x, begin_norm_axis=2)
    h = A.ffn(h, cfg.d_inner, dropout_rate=cfg.dropout)
    return x + L.dropout(h, cfg.dropout, dropout_implementation="upscale_in_train")


def decoder_layer(x, enc_out, cfg: TransformerConfig, self_mask, cross_mask,
                  cache: Optional[dict] = None):
    h = L.layer_norm(x, begin_norm_axis=2)
    if cache is not None:
        h, cache = A.multi_head_attention(h, num_heads=cfg.num_heads, causal=False,
                                          dropout_rate=0.0, cache=cache)
    else:
        h = A.multi_head_attention(h, num_heads=cfg.num_heads, causal=True,
                                   attn_mask=self_mask, dropout_rate=cfg.dropout,
                                   use_flash=cfg.use_flash)
    x = x + L.dropout(h, cfg.dropout, dropout_implementation="upscale_in_train")
    h = L.layer_norm(x, begin_norm_axis=2)
    h = A.multi_head_attention(h, keys=enc_out, num_heads=cfg.num_heads,
                               attn_mask=cross_mask, dropout_rate=cfg.dropout)
    x = x + L.dropout(h, cfg.dropout, dropout_implementation="upscale_in_train")
    h = L.layer_norm(x, begin_norm_axis=2)
    h = A.ffn(h, cfg.d_inner, dropout_rate=cfg.dropout)
    x = x + L.dropout(h, cfg.dropout, dropout_implementation="upscale_in_train")
    return (x, cache) if cache is not None else x


def encode(src_ids, cfg: TransformerConfig):
    dtype = jnp.dtype(cfg.dtype)
    x = _embed(src_ids, cfg.src_vocab, cfg.d_model, dtype, "src")
    x = x + A.positional_encoding(src_ids.shape[1], cfg.d_model, dtype)[None]
    x = L.dropout(x, cfg.dropout, dropout_implementation="upscale_in_train")
    mask = A.padding_mask(src_ids)
    with name_scope("encoder"):
        for _ in range(cfg.num_encoder_layers):
            x = encoder_layer(x, cfg, mask)
        x = L.layer_norm(x, begin_norm_axis=2)
    return x, mask


def decode(trg_ids, enc_out, cross_mask, cfg: TransformerConfig):
    dtype = jnp.dtype(cfg.dtype)
    x = _embed(trg_ids, cfg.trg_vocab, cfg.d_model, dtype, "trg")
    x = x + A.positional_encoding(trg_ids.shape[1], cfg.d_model, dtype)[None]
    x = L.dropout(x, cfg.dropout, dropout_implementation="upscale_in_train")
    with name_scope("decoder"):
        for _ in range(cfg.num_decoder_layers):
            x = decoder_layer(x, enc_out, cfg, None, cross_mask)
        x = L.layer_norm(x, begin_norm_axis=2)
    helper = LayerHelper("logits_proj")
    w = helper.create_parameter("w", (cfg.d_model, cfg.trg_vocab), dtype,
                                initializer=init.Xavier())
    return jnp.matmul(x, w)


def make_model(cfg: TransformerConfig):
    """Program fn: (src_ids[b,s], trg_ids[b,t], labels[b,t]) -> dict.
    Loss = label-smoothed CE over non-pad target tokens, matching the
    reference benchmark's objective."""

    def transformer(src_ids, trg_ids, labels):
        enc_out, src_mask = encode(src_ids, cfg)
        logits = decode(trg_ids, enc_out, src_mask, cfg)
        onehot = L.one_hot(labels, cfg.trg_vocab)
        smoothed = L.label_smooth(onehot, epsilon=cfg.label_smooth_eps)
        ce = L.softmax_with_cross_entropy(logits, smoothed, soft_label=True)
        nonpad = (labels != 0).astype(jnp.float32)
        token_count = jnp.maximum(nonpad.sum(), 1.0)
        loss = jnp.sum(ce[..., 0] * nonpad) / token_count
        return {"loss": loss, "logits": logits, "token_count": token_count}

    return transformer
