"""RecordIO — Python binding over the C++ core (paddle_tpu/native/
recordio.cc; reference: paddle/fluid/recordio/ + recordio_writer.py).

Builds the shared library on first use with g++ (no pybind11 in this
image — plain C ABI + ctypes). Provides:
- :class:`Writer` / :class:`Scanner` — raw byte records.
- ``write_arrays`` / ``read_arrays`` — numpy-tuple records with a tiny
  header (dtype/shape), the convert-reader-to-recordio capability
  (fluid.recordio_writer.convert_reader_to_recordio_file analog).
- ``reader_creator(path)`` — a reader-combinator-compatible creator.
"""

from __future__ import annotations

import ctypes
import io
import struct
from typing import Iterable, Iterator, Sequence, Tuple

import numpy as np

from .native import build_native

_lib = None


def _load_lib():
    global _lib
    if _lib is not None:
        return _lib
    so = build_native("recordio.cc", "librecordio.so",
                      extra_flags=("-shared", "-fPIC"), opt="-O3",
                      libs=("-lz",))
    lib = ctypes.CDLL(so)
    lib.rio_writer_open.restype = ctypes.c_void_p
    lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.rio_writer_write.restype = ctypes.c_int
    lib.rio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.rio_writer_close.restype = ctypes.c_int
    lib.rio_writer_close.argtypes = [ctypes.c_void_p]
    lib.rio_scanner_open.restype = ctypes.c_void_p
    lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
    lib.rio_scanner_next.restype = ctypes.c_int64
    lib.rio_scanner_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
    lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class Writer:
    def __init__(self, path: str, compress: bool = True, chunk_bytes: int = 1 << 20):
        lib = _load_lib()
        self._lib = lib
        self._h = lib.rio_writer_open(path.encode(), int(compress), chunk_bytes)
        if not self._h:
            raise IOError(f"cannot open {path} for writing")

    def write(self, record: bytes) -> None:
        rc = self._lib.rio_writer_write(self._h, record, len(record))
        if rc != 0:
            raise IOError("recordio write failed")

    def close(self) -> None:
        if self._h:
            rc = self._lib.rio_writer_close(self._h)
            self._h = None
            if rc != 0:
                raise IOError("recordio close/flush failed")

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class Scanner:
    def __init__(self, path: str):
        lib = _load_lib()
        self._lib = lib
        self._h = lib.rio_scanner_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path}")

    def __iter__(self) -> Iterator[bytes]:
        ptr = ctypes.POINTER(ctypes.c_uint8)()
        while True:
            n = self._lib.rio_scanner_next(self._h, ctypes.byref(ptr))
            if n == -1:
                break
            if n == -2:
                raise IOError("recordio corruption detected (crc/format)")
            yield ctypes.string_at(ptr, n)

    def close(self) -> None:
        if self._h:
            self._lib.rio_scanner_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


# -- numpy tuple records -----------------------------------------------------


def _pack_arrays(arrays: Sequence[np.ndarray]) -> bytes:
    out = io.BytesIO()
    out.write(struct.pack("<I", len(arrays)))
    for a in arrays:
        a = np.ascontiguousarray(a)
        dt = a.dtype.str.encode()
        out.write(struct.pack("<I", len(dt)))
        out.write(dt)
        out.write(struct.pack("<I", a.ndim))
        out.write(struct.pack(f"<{a.ndim}q" if a.ndim else "<", *a.shape))
        raw = a.tobytes()
        out.write(struct.pack("<Q", len(raw)))
        out.write(raw)
    return out.getvalue()


def _unpack_arrays(rec: bytes) -> Tuple[np.ndarray, ...]:
    buf = io.BytesIO(rec)
    (n,) = struct.unpack("<I", buf.read(4))
    arrays = []
    for _ in range(n):
        (dl,) = struct.unpack("<I", buf.read(4))
        dt = np.dtype(buf.read(dl).decode())
        (nd,) = struct.unpack("<I", buf.read(4))
        shape = struct.unpack(f"<{nd}q", buf.read(8 * nd)) if nd else ()
        (rl,) = struct.unpack("<Q", buf.read(8))
        arrays.append(np.frombuffer(buf.read(rl), dtype=dt).reshape(shape))
    return tuple(arrays)


def write_arrays(path: str, samples: Iterable[Sequence[np.ndarray]],
                 compress: bool = True) -> int:
    """convert_reader_to_recordio_file analog: write tuple-of-array
    samples; returns count."""
    n = 0
    with Writer(path, compress=compress) as w:
        for s in samples:
            w.write(_pack_arrays([np.asarray(x) for x in s]))
            n += 1
    return n


def read_arrays(path: str) -> Iterator[Tuple[np.ndarray, ...]]:
    with Scanner(path) as s:
        for rec in s:
            yield _unpack_arrays(rec)


def reader_creator(path: str):
    """Reader-creator over a recordio file (open_recordio_file analog,
    layers/io.py:349)."""

    def reader():
        yield from read_arrays(path)

    return reader
