"""Layer library — the ``fluid.layers`` surface (python/paddle/fluid/layers/).

Every name in the union of the reference's ``layers/*`` ``__all__``
lists (199 public + 5 layer_function_generator helpers) is importable
from this namespace — tests/test_layers_parity.py pins the full list so
the claim cannot drift."""

from . import attention, beam_search, control_flow, crf, ctc, detection
from . import io, layer_function_generator, nn, ops, rnn, sequence, tensor
from .beam_search import beam_search_decode, beam_search_decode_lod
from .control_flow import (
    DynamicRNN,
    IfElse,
    Print,
    StaticRNN,
    Switch,
    While,
    array_length,
    array_read,
    array_write,
    create_array,
)
from .crf import crf_decoding, linear_chain_crf
from .layer_function_generator import (
    autodoc,
    deprecated,
    generate_layer_fn,
    generate_layer_fn_noattr,
    templatedoc,
)
from .ctc import ctc_greedy_decoder, edit_distance, warpctc
from .io import (
    Preprocessor,
    PyReader,
    batch,
    data,
    double_buffer,
    load,
    open_files,
    py_reader,
    random_data_generator,
    read_file,
    shuffle,
)
from .attention import (
    ffn,
    multi_head_attention,
    padding_mask,
    positional_encoding,
    scaled_dot_product_attention,
)
from .detection import (
    anchor_generator,
    bipartite_match,
    box_coder,
    density_prior_box,
    detection_map,
    detection_output,
    generate_proposal_labels,
    generate_proposals,
    iou_similarity,
    multi_box_head,
    multiclass_nms,
    polygon_box_transform,
    prior_box,
    roi_align,
    roi_perspective_transform,
    roi_pool,
    rpn_target_assign,
    ssd_loss,
    target_assign,
    yolo_box,
)
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .rnn import (
    dynamic_gru,
    dynamic_lstm,
    dynamic_lstmp,
    gru_unit,
    lstm_unit,
    rnn as rnn_scan,
)
from .sequence import (
    LoDTensor,
    create_lod_tensor,
    create_random_int_lodtensor,
    lod_reset,
    reorder_lod_tensor_by_rank,
    sequence_concat,
    sequence_conv,
    sequence_enumerate,
    sequence_expand,
    sequence_expand_as,
    sequence_first_step,
    sequence_last_step,
    sequence_mask,
    sequence_pad,
    sequence_pool,
    sequence_reshape,
    sequence_reverse,
    sequence_scatter,
    sequence_slice,
    sequence_softmax,
    sequence_unpad,
)
from .tensor import *  # noqa: F401,F403
from .tensor import _sum_layer as sum  # noqa: A004  (reference API name)

# names the reference's fluid.layers re-exports from sibling modules:
# metric ops (layers/metric_op.py), LR decays
# (layers/learning_rate_scheduler.py), and create_parameter
# (layers/tensor.py → our framework)
from ..framework import create_parameter
from ..lr_scheduler import (
    append_LARS,
    cosine_decay,
    exponential_decay,
    inverse_time_decay,
    natural_exp_decay,
    noam_decay,
    piecewise_decay,
    polynomial_decay,
)
from ..metrics import accuracy, auc, chunk_eval
