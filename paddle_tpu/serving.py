"""Production serving runtime: bounded-queue predictor server with
request validation, shape bucketing, deadlines, a circuit breaker, hot
model reload, and health signals.

The reference's inference engine contract (NativePaddlePredictor
Init/Prepare/Run/Clone, api_impl.cc:64) covers a single process calling
``Run`` in a loop; the serving story around it — capacity limits, model
swaps, health checks — lived in the fleet layer. Here the AOT-once
discipline that makes XLA executables predictable under load gets the
surrounding runtime, the serving-side sibling of the fault-tolerant
*training* runtime in :mod:`paddle_tpu.resilience`:

- **Typed request validation** — a malformed request (missing/extra
  feed key, shape/dtype mismatch, non-finite payload) raises
  :class:`InvalidRequest` naming the offending field at ``submit``
  time, before it can occupy queue capacity or abort an executable.
- **Shape bucketing** — requests are padded up to a fixed,
  precompiled bucket set (``save_inference_model(batch_buckets=...)``),
  so ragged or adversarial batch sizes can never trigger a recompile on
  the request path; off-bucket shapes are rejected, and per-bucket
  compile counts are pinned after warmup (``metrics.report()``'s
  ``compiles_since_warmup`` stays 0).
- **Bounded queue + deadlines** — saturation raises
  :class:`ServerOverloaded` (never unbounded memory); a request whose
  deadline passes while queued is dropped without executing.
- **Watchdog + circuit breaker** — a dispatch that hangs past the
  watchdog timeout, or repeated executable failures, trip the breaker:
  subsequent submits fail fast with :class:`CircuitOpen`, and after a
  cooldown a half-open probe request recovers the pool.
- **Hot reload** — :meth:`PredictorServer.reload` loads and
  CRC-validates a new artifact off-thread (the
  ``resilience.write_manifest`` manifest written by
  ``save_inference_model``), canaries it on a golden feed, and
  atomically swaps it in; any failure rolls back with zero dropped
  in-flight requests.
- **Drain + health** — :meth:`PredictorServer.close(drain=True)`
  finishes queued work before stopping (pair with
  :class:`~paddle_tpu.resilience.PreemptionHandler` for SIGTERM);
  :meth:`health` is the readiness/liveness state machine and
  :class:`ServingMetrics` the latency/queue/error counters, with a
  ``report()`` mirroring ``Trainer.pipeline_report()``.
"""

from __future__ import annotations

import dataclasses
import logging
import queue as _queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .core.errors import EnforceError
from .fleet import batching as _batching
from .io import InvalidRequest  # noqa: F401  (re-exported: submit raises it)


def _log():
    return logging.getLogger("paddle_tpu.serving")


# -- typed serving errors -----------------------------------------------------


class ServingError(EnforceError):
    """Base of every typed serving-runtime error."""


class ServerOverloaded(ServingError):
    """The bounded work queue is full — shed load instead of growing
    memory. Carries ``queue_depth``/``capacity`` for the reject reply."""

    def __init__(self, queue_depth: int, capacity: int):
        super().__init__(f"server overloaded: queue depth {queue_depth} at "
                         f"capacity {capacity}")
        self.queue_depth = queue_depth
        self.capacity = capacity


class DeadlineExceeded(ServingError, TimeoutError):
    """The request's deadline passed before a result was produced."""


class CircuitOpen(ServingError):
    """The circuit breaker is open (recent failures/hangs): failing fast
    instead of queueing onto a broken executable. ``retry_after`` is the
    seconds until the next half-open probe is allowed."""

    def __init__(self, retry_after: float):
        super().__init__(f"circuit breaker open: retry after "
                         f"{max(0.0, retry_after):.2f}s")
        self.retry_after = retry_after


class WorkerHung(ServingError):
    """A dispatch exceeded the watchdog timeout; the worker was
    abandoned and its request failed fast."""


class ServerClosed(ServingError):
    """submit() after close()/drain started — also the outcome of a
    request that was accepted but NEVER dispatched when its server
    died or stopped. A router may safely resubmit such a request
    elsewhere (it provably never executed); see
    :class:`~paddle_tpu.fleet.FleetRouter`."""


class ReplicaDied(ServingError):
    """The serving replica died (``PredictorServer.kill`` — the
    in-process stand-in for the process being killed) while this
    request was DISPATCHED on one of its workers. At-most-once: the
    request may or may not have executed, so it is surfaced exactly
    once as this error and never retried — the serving mirror of
    ``PSClient.push``'s ``PushUndelivered``."""


class ReloadFailed(ServingError):
    """Hot reload rejected (corrupt artifact, incompatible signature, or
    canary failure) — the previous model keeps serving."""

    def __init__(self, dirname: str, reason: str):
        super().__init__(f"reload of {dirname!r} failed: {reason} "
                         "(previous model still serving)")
        self.dirname = dirname
        self.reason = reason


# -- latency histogram --------------------------------------------------------

# log-spaced upper bounds, 50us .. ~80s, ratio ~1.3 (55 buckets): fixed
# memory, ~15% percentile resolution — the usual serving-histogram trade
_HIST_BOUNDS = tuple(50e-6 * (1.3 ** i) for i in range(55))


class LatencyHistogram:
    """Fixed-bucket log-scale latency histogram (seconds in,
    percentiles out). Not thread-safe on its own — ServingMetrics holds
    the lock."""

    def __init__(self):
        self.counts = [0] * (len(_HIST_BOUNDS) + 1)
        self.total = 0
        self.sum_s = 0.0
        self.max_s = 0.0

    def record(self, seconds: float) -> None:
        import bisect
        self.counts[bisect.bisect_left(_HIST_BOUNDS, seconds)] += 1
        self.total += 1
        self.sum_s += seconds
        self.max_s = max(self.max_s, seconds)

    def percentile(self, p: float) -> Optional[float]:
        """Upper bound of the bucket holding the p-th percentile (p in
        [0, 100]); None when empty."""
        if not self.total:
            return None
        rank = p / 100.0 * self.total
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                return (_HIST_BOUNDS[i] if i < len(_HIST_BOUNDS)
                        else self.max_s)
        return self.max_s


# -- circuit breaker ----------------------------------------------------------


@dataclasses.dataclass
class BreakerPolicy:
    """Circuit-breaker tuning: ``failure_threshold`` consecutive
    failures (or one watchdog hang) trip it open; after ``cooldown``
    seconds one half-open probe request is let through — success closes
    the breaker, failure re-opens it for another cooldown."""

    failure_threshold: int = 5
    cooldown: float = 30.0


class CircuitBreaker:
    """closed → open → half_open → closed state machine (thread-safe).

    ``on_trip(reason)`` fires AFTER the lock is released whenever the
    breaker (re)opens — reasons ``"failures"`` (threshold trip),
    ``"hang"`` (watchdog :meth:`trip`), ``"probe_failure"`` (half-open
    probe failed). The server uses it to journal the state change and
    flight-record the trip; a raising callback is swallowed (telemetry
    must never wedge the breaker)."""

    def __init__(self, policy: Optional[BreakerPolicy] = None,
                 on_trip: Optional[Callable[[str], Any]] = None):
        self.policy = policy or BreakerPolicy()
        self.on_trip = on_trip
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive = 0
        self._open_until = 0.0
        self._probe_out = False
        self.trips = 0

    def _fire_on_trip(self, reason: str) -> None:
        if self.on_trip is None:
            return
        try:
            self.on_trip(reason)
        except Exception:
            pass

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def acquire(self) -> Optional[str]:
        """Admission check for one request. Returns ``"pass"``
        (breaker closed), ``"probe"`` (the one half-open probe), or
        ``None`` (open: fail fast)."""
        with self._lock:
            if self._state == "closed":
                return "pass"
            now = time.monotonic()
            if self._state == "open" and now >= self._open_until:
                self._state = "half_open"
                self._probe_out = False
            if self._state == "half_open" and not self._probe_out:
                self._probe_out = True
                return "probe"
            return None

    def retry_after(self) -> float:
        with self._lock:
            return self._open_until - time.monotonic()

    def cancel(self, token: Optional[str]) -> None:
        """A request admitted but never executed (validation reject,
        queue-full reject) returns its probe slot."""
        if token != "probe":
            return
        with self._lock:
            if self._state == "half_open":
                self._probe_out = False

    def record(self, token: Optional[str], success: bool) -> None:
        fire = None
        with self._lock:
            if success:
                self._consecutive = 0
                # only the half-open PROBE closes an open breaker — and
                # only while the breaker is still waiting on it: a stale
                # success (an abandoned hung worker — or hung probe —
                # finally returning after a fresh trip) must not mask a
                # tripped pool or bypass the cooldown that trip started
                if token == "probe" and self._state == "half_open":
                    self._state = "closed"
                    self._probe_out = False
                return
            elif token == "probe" or self._state == "half_open":
                self._reopen()
                fire = "probe_failure"
            else:
                self._consecutive += 1
                if self._state == "closed" and \
                        self._consecutive >= self.policy.failure_threshold:
                    self._trip()
                    fire = "failures"
        if fire:
            self._fire_on_trip(fire)

    def trip(self) -> None:
        """Force the breaker open (the watchdog's hung-dispatch path —
        one hang is conclusive, no threshold)."""
        with self._lock:
            self._trip()
        self._fire_on_trip("hang")

    def _trip(self):
        self._state = "open"
        self._open_until = time.monotonic() + self.policy.cooldown
        self._probe_out = False
        self.trips += 1
        _log().warning("circuit breaker OPEN for %.2fs (%d trips)",
                       self.policy.cooldown, self.trips)

    def _reopen(self):
        self._state = "open"
        self._open_until = time.monotonic() + self.policy.cooldown
        self._probe_out = False


# -- metrics ------------------------------------------------------------------


class ServingMetrics:
    """Thread-safe serving counters + latency histogram, surfaced via
    :meth:`report` (the serving mirror of
    ``PipelineMetrics.report``/``Trainer.pipeline_report()``)."""

    _COUNTERS = ("submitted", "completed", "rejected_invalid",
                 "rejected_overload", "rejected_breaker", "timeouts",
                 "errors", "hangs", "workers_replaced", "reloads",
                 "reload_failures", "coalesced_batches",
                 "coalesced_requests")

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with self._lock:
            for c in self._COUNTERS:
                setattr(self, c, 0)
            self.hist = LatencyHistogram()

    def bump(self, counter: str, by: int = 1):
        with self._lock:
            setattr(self, counter, getattr(self, counter) + by)

    def record_latency(self, seconds: float):
        with self._lock:
            self.hist.record(seconds)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out = {c: getattr(self, c) for c in self._COUNTERS}
            h = self.hist
            out["latency_ms"] = {
                "p50": _ms(h.percentile(50)), "p95": _ms(h.percentile(95)),
                "p99": _ms(h.percentile(99)), "max": _ms(h.max_s or None),
                "mean": _ms(h.sum_s / h.total if h.total else None),
                "count": h.total,
            }
            # the raw histogram (bucket upper bounds in SECONDS +
            # per-bucket counts, one overflow bucket past the last
            # bound): the Prometheus exporter emits a real _bucket
            # series from this instead of re-deriving from percentiles
            out["latency_hist"] = {
                "bounds_s": list(_HIST_BOUNDS),
                "counts": list(h.counts),
                "sum_s": h.sum_s,
                "count": h.total,
            }
            return out

    def telemetry_families(self, inst: str = "0") -> list:
        """The same counters + histogram as registry metric families
        (``paddle_tpu_serving_*``) — called by the PredictorServer's
        scrape-time collector, so the exported series agree with
        :meth:`report` by construction."""
        from .telemetry.registry import counter_family, histogram_family

        snap = self.snapshot()
        labels = {"inst": inst}
        fams = [
            counter_family("paddle_tpu_serving_submitted_total",
                           "Requests accepted into the queue",
                           [(labels, snap["submitted"])]),
            counter_family("paddle_tpu_serving_completed_total",
                           "Requests completed successfully",
                           [(labels, snap["completed"])]),
            counter_family(
                "paddle_tpu_serving_rejected_total",
                "Requests rejected at submit (by reason)",
                [({**labels, "reason": r}, snap[f"rejected_{r}"])
                 for r in ("invalid", "overload", "breaker")]),
            counter_family("paddle_tpu_serving_timeouts_total",
                           "Requests dropped at their deadline",
                           [(labels, snap["timeouts"])]),
            counter_family("paddle_tpu_serving_errors_total",
                           "Requests failed by an executable error",
                           [(labels, snap["errors"])]),
            counter_family("paddle_tpu_serving_hangs_total",
                           "Dispatches abandoned by the watchdog",
                           [(labels, snap["hangs"])]),
            counter_family("paddle_tpu_serving_workers_replaced_total",
                           "Workers replaced after a watchdog hang",
                           [(labels, snap["workers_replaced"])]),
            counter_family(
                "paddle_tpu_serving_reloads_total",
                "Hot-reload attempts (by outcome)",
                [({**labels, "outcome": "ok"}, snap["reloads"]),
                 ({**labels, "outcome": "failed"},
                  snap["reload_failures"])]),
            counter_family("paddle_tpu_serving_coalesced_batches_total",
                           "Dispatches that coalesced >1 request",
                           [(labels, snap["coalesced_batches"])]),
            counter_family("paddle_tpu_serving_coalesced_requests_total",
                           "Requests served inside a coalesced dispatch",
                           [(labels, snap["coalesced_requests"])]),
        ]
        h = snap["latency_hist"]
        fams.append(histogram_family(
            "paddle_tpu_serving_latency_seconds",
            "End-to-end served latency (queue wait included)",
            labels, h["bounds_s"], h["counts"], h["sum_s"], h["count"]))
        return fams


def _ms(seconds: Optional[float]) -> Optional[float]:
    return None if seconds is None else round(seconds * 1e3, 4)


# -- requests -----------------------------------------------------------------


class _Request:
    __slots__ = ("feed", "n", "bucket", "deadline", "token", "done",
                 "value", "error", "submitted", "completed", "span")

    def __init__(self, feed, n, bucket, deadline, token, span=None):
        self.feed = feed
        self.n = n
        self.bucket = bucket
        self.deadline = deadline      # absolute monotonic, or None
        self.token = token            # breaker admission token
        self.span = span              # trace id minted at submit
        self.done = threading.Event()
        self.value = None
        self.error: Optional[BaseException] = None
        self.submitted = time.monotonic()
        self.completed: Optional[float] = None


class PendingResult:
    """Handle returned by :meth:`PredictorServer.submit`."""

    def __init__(self, req: _Request):
        self._req = req

    @property
    def span(self) -> Optional[str]:
        """The request's trace id (minted at submit): every journal
        event of its lifecycle — submit, worker dispatch, completion,
        a watchdog hang — carries it."""
        return self._req.span

    def done(self) -> bool:
        return self._req.done.is_set()

    @property
    def latency(self) -> Optional[float]:
        """End-to-end seconds (queue wait included) once complete."""
        r = self._req
        return None if r.completed is None else r.completed - r.submitted

    def result(self, timeout: Optional[float] = None):
        """Block for the outcome; raises the request's typed error, or
        :class:`DeadlineExceeded` when ``timeout``/the request deadline
        passes first (the request itself is then dropped unexecuted by
        the worker that dequeues it)."""
        r = self._req
        if timeout is None and r.deadline is not None:
            timeout = max(0.0, r.deadline - time.monotonic()) + 1.0
        if not r.done.wait(timeout):
            raise DeadlineExceeded(
                f"no result within {timeout:.2f}s (request still queued or "
                "executing; it will be dropped at its deadline)")
        if r.error is not None:
            raise r.error
        return r.value


# -- the server ---------------------------------------------------------------


class _Worker:
    __slots__ = ("thread", "busy_since", "request", "group", "carry",
                 "abandoned", "index")

    def __init__(self, index: int):
        self.index = index
        self.thread: Optional[threading.Thread] = None
        self.busy_since: Optional[float] = None
        self.request: Optional[_Request] = None
        # the full coalesced group behind `request` (None = pad-alone)
        self.group: Optional[List[_Request]] = None
        # requests pulled while coalescing that could not join the
        # forming batch — served FIRST on the next loop iteration
        self.carry: List[_Request] = []
        self.abandoned = False


class PredictorServer:
    """Bounded-queue serving runtime over a pool of ``Predictor.clone()``
    workers (one clone per worker thread — the PaddlePredictor::Clone
    contract; the executable and device weights are shared).

    ``predictor`` needs the :class:`paddle_tpu.io.Predictor` surface:
    ``clone()``, ``run(feed)``, ``feed_names``, ``batch_buckets``,
    ``batched_feeds``, ``feed_spec(b)``, ``validate_feed(feed,
    allow_padding=)`` — the fault-injection wrappers in
    ``paddle_tpu.testing.faults`` duck-type it.

    Request flow: :meth:`submit` validates structurally (typed
    :class:`InvalidRequest`), checks the breaker (fail-fast
    :class:`CircuitOpen`), and enqueues (reject
    :class:`ServerOverloaded` when full) → a worker pads the batch up to
    its precompiled bucket, executes, slices the outputs back to the
    request's batch size, and completes the :class:`PendingResult`.
    :meth:`run` is the synchronous wrapper.

    ``golden_feed`` (+ optional ``canary_check(outputs)``) gates hot
    reloads: a candidate model must serve the golden feed with finite
    outputs (and pass ``canary_check``) before it is swapped in.

    ``batch_policy`` (a :class:`paddle_tpu.fleet.BatchPolicy`) turns on
    **continuous batching**: workers coalesce queued requests into the
    largest precompiled bucket that fits within the policy's wait
    budget, slice outputs back per caller by row span, and preserve
    every per-request contract — deadlines, spans, validation, typed
    errors — with results bit-identical to pad-alone dispatch and zero
    new compiles (the same bucket executables serve, just fuller)."""

    def __init__(self, predictor, workers: int = 2, queue_size: int = 32,
                 default_deadline: Optional[float] = None,
                 breaker: Optional[BreakerPolicy] = None,
                 watchdog_timeout: Optional[float] = 60.0,
                 golden_feed: Optional[Dict[str, Any]] = None,
                 canary_check: Optional[Callable[[Any], Any]] = None,
                 reject_nonfinite: bool = True,
                 batch_policy=None,
                 warmup: bool = True, start: bool = True):
        from . import io as _io

        self._io = _io
        # published atomically under _model_lock; reads are deliberately
        # lock-free reference snapshots (reloads are serialized by
        # _reload_lock, so any read sees a complete predictor)
        self._predictor = predictor   # lint: allow(thread:unguarded-access)
        self._generation = 1          # lint: allow(thread:unguarded-access)
        self._model_lock = threading.Lock()
        self._reload_lock = threading.Lock()
        self._last_reload_error: Optional[BaseException] = None
        self.num_workers = int(workers)
        self.queue_size = int(queue_size)
        self.default_deadline = default_deadline
        self.watchdog_timeout = watchdog_timeout
        self.golden_feed = golden_feed
        self.canary_check = canary_check
        self.reject_nonfinite = bool(reject_nonfinite)
        # continuous batching (fleet.batching.BatchPolicy): workers
        # coalesce queued requests into the largest precompiled bucket
        # within the policy's wait budget; None = pad-alone (the PR-5
        # behavior, unchanged)
        self.batch_policy = batch_policy
        self._do_warmup = bool(warmup)
        self._queue: _queue.Queue = _queue.Queue(maxsize=self.queue_size)
        self._complete_lock = threading.Lock()
        self.metrics = ServingMetrics()
        # unified telemetry: journal spans per request, a scrape-time
        # collector in the process registry (the `inst` label keeps
        # replicas apart), flight dumps on hangs/breaker trips
        from .telemetry import get_journal, get_registry
        self.journal = get_journal()
        self.telemetry_inst = get_registry().next_instance("serving")
        self._telemetry_server = None
        # push shipping: PDTPU_TELEMETRY_ADDR streams this process's
        # journal + registry snapshots to the telemetry collector (a
        # remote replica inherits the env var and ships on its own) —
        # ship_to() is the explicit door; never raises into serving
        from .telemetry.shipper import maybe_auto_ship
        maybe_auto_ship()
        self.breaker = CircuitBreaker(breaker, on_trip=self._on_breaker_trip)
        self._workers: List[_Worker] = []
        self._watchdog: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._state = "starting"
        self._state_lock = threading.Lock()
        self._started_at = time.monotonic()
        self._pinned_compiles: Optional[int] = None
        # registered last: a scrape must never see a half-built server
        self._telemetry_cid = _register_server_telemetry(self)
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "PredictorServer":
        """Spawn workers + watchdog, warm every bucket once, pin the
        compile count, flip readiness."""
        with self._state_lock:
            if self._state != "starting":
                return self
        for i in range(self.num_workers):
            self._spawn_worker(i)
        if self.watchdog_timeout is not None:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, daemon=True,
                name="pdtpu-serving-watchdog")
            self._watchdog.start()
        if self._do_warmup:
            self._warmup(self._predictor)
        # pin: any AOT compile after this point is a serving-contract
        # violation the metrics report makes visible
        self._pinned_compiles = self._io.aot_compile_count()
        with self._state_lock:
            self._state = "ready"
        return self

    def _warmup(self, predictor) -> None:
        """One execution per bucket (golden feed where it fits, zeros
        otherwise): pages weights/executables in so the first real
        request sees steady-state latency."""
        clone = predictor.clone()
        for b in predictor.batch_buckets:
            feed = self._bucket_feed(predictor, b)
            out = clone.run(feed)
            _block_on(out)

    def _bucket_feed(self, predictor, bucket: int) -> Dict[str, np.ndarray]:
        spec = predictor.feed_spec(bucket)
        golden = self.golden_feed or {}
        feed = {}
        for k, (shape, dtype) in spec.items():
            if k in golden:
                v = np.asarray(golden[k])
                if k in predictor.batched_feeds:
                    from .io import _resize_batch
                    v = _resize_batch(v, bucket)
                feed[k] = v.astype(dtype, copy=False)
            else:
                feed[k] = np.zeros(shape, dtype)
        return feed

    def close(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Stop the server. ``drain=True`` (graceful: the SIGTERM path)
        finishes every queued request first; ``drain=False`` fails
        queued requests fast with :class:`ServerClosed`. Idempotent."""
        with self._state_lock:
            if self._state == "stopped":
                return
            self._state = "draining" if drain else "stopping"
        deadline = None if timeout is None else time.monotonic() + timeout
        if drain:
            # abandoned (hung) workers never go idle — waiting on them
            # would spin the SIGTERM drain forever; their requests were
            # already failed fast by the watchdog. Carried (coalescer-
            # deferred) requests count as pending work too.
            while not self._queue.empty() or any(
                    (w.busy_since is not None or w.carry)
                    and not w.abandoned
                    for w in self._workers):
                if deadline is not None and time.monotonic() > deadline:
                    break
                time.sleep(0.005)
        self._stop.set()
        # fail anything STILL queued (drain=False teardown, or a drain
        # that hit its timeout): workers exit without dequeuing once the
        # stop flag is set, and a stranded request would block its
        # client's result() forever; probe tokens release their slot
        while True:
            try:
                req = self._queue.get_nowait()
            except _queue.Empty:
                break
            self.breaker.cancel(req.token)
            self._complete(req, error=ServerClosed("server stopping"))
        for w in self._workers:
            if w.abandoned:
                continue   # wedged in a dispatch; daemon thread, no join
            if w.thread is not None and w.thread is not threading.current_thread():
                try:
                    w.thread.join(timeout=5.0)
                except RuntimeError:   # raced a spawn: daemon exits solo
                    pass
        # abandoned workers never run their loop-exit cleanup: fail any
        # carried (never-dispatched) request they still hold
        for w in self._workers:
            for r in w.carry:
                self.breaker.cancel(r.token)
                self._complete(r, error=ServerClosed("server stopping"))
            w.carry = []
        if self._watchdog is not None:
            self._watchdog.join(timeout=5.0)
        with self._state_lock:
            self._state = "stopped"
        if self._telemetry_server is not None:
            self._telemetry_server.close()
            self._telemetry_server = None
        # a closed server must not keep exporting live-looking queue/
        # worker gauges for as long as a caller holds a reference
        from .telemetry import get_registry
        get_registry().remove_collector(self._telemetry_cid)

    def kill(self, reason: str = "replica killed") -> None:
        """Abrupt replica death — the in-process stand-in for the
        serving process being ``kill -9``'d, used by fleet drills
        (``testing.faults.kill_server``) and exercised by
        :class:`~paddle_tpu.fleet.FleetRouter`'s retry contract. No
        drain, no joins:

        - requests still QUEUED (or coalescer-carried) were provably
          never dispatched: they fail with :class:`ServerClosed`, which
          a router may safely resubmit to another replica;
        - requests DISPATCHED on a worker fail with
          :class:`ReplicaDied` exactly once and are never retried
          (at-most-once — the execution may or may not have happened);
        - the flight recorder captures the kill with the first
          in-flight request's span, so the post-mortem shows exactly
          what the replica was serving when it died.

        Idempotent; a later :meth:`close` is a no-op."""
        with self._state_lock:
            if self._state == "stopped":
                return
            self._state = "stopped"
        self._stop.set()
        died = []
        for w in self._workers:
            grp = list(w.group or ())
            if not grp and w.request is not None:
                grp = [w.request]
            w.abandoned = True
            for r in grp:
                if self._complete(r, error=ReplicaDied(reason)):
                    died.append(r.span)
            for r in w.carry:
                self.breaker.cancel(r.token)
                self._complete(r, error=ServerClosed(reason))
            w.carry = []
        requeued = 0
        while True:
            try:
                req = self._queue.get_nowait()
            except _queue.Empty:
                break
            self.breaker.cancel(req.token)
            self._complete(req, error=ServerClosed(reason))
            requeued += 1
        self.journal.emit("serving.killed", span=died[0] if died else None,
                          inst=self.telemetry_inst, reason=reason,
                          inflight=len(died), queued=requeued)
        from .telemetry import flight_dump, get_registry
        flight_dump("replica_killed", span=died[0] if died else None,
                    detail={"reason": reason, "inflight": len(died),
                            "inflight_spans": died, "queued": requeued,
                            "inst": self.telemetry_inst})
        _log().error("replica killed (%s): %d in-flight failed "
                     "at-most-once, %d never-dispatched failed retryable",
                     reason, len(died), requeued)
        if self._telemetry_server is not None:
            self._telemetry_server.close()
            self._telemetry_server = None
        get_registry().remove_collector(self._telemetry_cid)

    def __enter__(self) -> "PredictorServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=True)

    # -- request path --------------------------------------------------------

    def submit(self, feed: Dict[str, Any],
               deadline: Optional[float] = None,
               span: Optional[str] = None) -> PendingResult:
        """Validate + enqueue one request; returns a
        :class:`PendingResult`. ``deadline`` is seconds from now (falls
        back to ``default_deadline``); raises :class:`InvalidRequest`,
        :class:`CircuitOpen`, :class:`ServerOverloaded`, or
        :class:`ServerClosed` — all typed, all naming the reason.
        ``span`` adopts an externally-minted trace id (the wire trace
        token of a cross-process front door) instead of minting one —
        both processes' journals then carry ONE id end to end."""
        with self._state_lock:
            state = self._state
        if state in ("draining", "stopping", "stopped"):
            raise ServerClosed(f"server is {state}")
        if state == "starting":
            raise ServerClosed("server not started (call start())")
        # the request's trace id is minted HERE, at submit (unless the
        # front door handed one over the wire): every journal event of
        # its life (queue, worker dispatch, outcome, a watchdog hang)
        # carries it — PendingResult.span exposes it
        span = span or self.journal.new_span()
        token = self.breaker.acquire()
        if token is None:
            self.metrics.bump("rejected_breaker")
            self.journal.emit("serving.reject", span=span,
                              inst=self.telemetry_inst, reason="breaker")
            raise CircuitOpen(self.breaker.retry_after())
        try:
            with self._model_lock:
                predictor = self._predictor
            n, bucket = predictor.validate_feed(feed, allow_padding=True)
            if self.reject_nonfinite:
                _check_finite(feed, predictor.feed_names)
        except InvalidRequest as e:
            self.breaker.cancel(token)
            self.metrics.bump("rejected_invalid")
            self.journal.emit("serving.reject", span=span,
                              inst=self.telemetry_inst, reason="invalid",
                              field=getattr(e, "field", None))
            raise
        except BaseException:
            # validation can also raise raw numpy errors (e.g. a ragged
            # nested list in np.asarray): the admission token — possibly
            # THE half-open probe slot — must still go back, or the
            # breaker wedges in half_open rejecting everything forever
            self.breaker.cancel(token)
            raise
        rel = self.default_deadline if deadline is None else deadline
        req = _Request(feed, n, bucket,
                       None if rel is None else time.monotonic() + rel,
                       token, span=span)
        # journaled BEFORE the enqueue: a fast worker can dequeue and
        # emit serving.dispatch microseconds after put_nowait, and the
        # span's timeline must never read dispatch-before-submit (an
        # overload reject after this event is an accurate submit→reject
        # record of the attempt)
        self.journal.emit("serving.submit", span=span,
                          inst=self.telemetry_inst, n=n, bucket=bucket,
                          deadline_s=rel, queue_depth=self._queue.qsize())
        # state re-check + enqueue are ATOMIC under the state lock:
        # close() flips the state under the same lock before draining,
        # so a request can never slip into the queue after the drain
        # loop decided it was empty (it would hang forever un-serviced)
        with self._state_lock:
            if self._state != "ready":
                self.breaker.cancel(token)
                raise ServerClosed(f"server is {self._state}")
            try:
                self._queue.put_nowait(req)
            except _queue.Full:
                self.breaker.cancel(token)
                self.metrics.bump("rejected_overload")
                self.journal.emit("serving.reject", span=span,
                                  inst=self.telemetry_inst,
                                  reason="overload",
                                  queue_depth=self._queue.qsize())
                raise ServerOverloaded(self._queue.qsize(),
                                       self.queue_size) from None
        self.metrics.bump("submitted")
        return PendingResult(req)

    def run(self, feed: Dict[str, Any], timeout: Optional[float] = None):
        """Synchronous submit+wait (``timeout`` doubles as the request
        deadline when no ``default_deadline`` is configured)."""
        deadline = timeout if self.default_deadline is None else None
        return self.submit(feed, deadline=deadline).result(timeout)

    # -- worker machinery ----------------------------------------------------

    def _spawn_worker(self, index: int) -> _Worker:
        w = _Worker(index)
        w.thread = threading.Thread(target=self._worker_loop, args=(w,),
                                    daemon=True,
                                    name=f"pdtpu-serving-worker-{index}")
        # started BEFORE it is registered: close() joins every
        # registered worker, and joining a not-yet-started thread
        # raises RuntimeError — a close() racing the watchdog's
        # replacement spawn must never see one (the daemon loop polls
        # the stop flag, so a started-but-unregistered worker still
        # shuts down cleanly on its own)
        w.thread.start()
        self._workers.append(w)
        return w

    def _admit(self, req: _Request) -> Optional[_Request]:
        """Dequeue-time admission, shared by pad-alone dispatch and the
        coalescing collector: a request whose deadline passed while
        queued is dropped WITHOUT executing (the clean-cancel half of
        the deadline contract — its breaker token goes back too, an
        expired half-open PROBE must release its slot or the breaker
        wedges in half_open rejecting everything forever); a request
        admitted before the breaker tripped fails fast instead of
        running the broken executable again. Returns the request, or
        None after completing it with its typed outcome."""
        now = time.monotonic()
        if req.deadline is not None and now > req.deadline:
            self.breaker.cancel(req.token)
            self.metrics.bump("timeouts")
            self.journal.emit("serving.expired", span=req.span,
                              inst=self.telemetry_inst,
                              late_s=round(now - req.deadline, 6))
            self._complete(req, error=DeadlineExceeded(
                f"deadline passed {now - req.deadline:.3f}s before "
                "dispatch"))
            return None
        if self.breaker.state == "open" and req.token == "pass":
            self.metrics.bump("rejected_breaker")
            self.journal.emit("serving.reject", span=req.span,
                              inst=self.telemetry_inst,
                              reason="breaker_queued")
            self._complete(req, error=CircuitOpen(
                self.breaker.retry_after()))
            return None
        return req

    def _coalesce(self, w: _Worker, first: _Request) -> List[_Request]:
        """Form a coalesced group seeded by ``first``: already-queued
        requests are taken for free, then the worker waits up to the
        policy's ``max_wait_ms`` past ``first``'s submit (never past
        the tightest deadline in the forming group) for more. Stops at
        the largest precompiled bucket, the policy's ``max_requests``,
        or the first incompatible candidate (different non-batched feed
        bytes, or it would overflow the bucket) — which is CARRIED and
        seeds this worker's next dispatch, never reordered behind later
        traffic. Every candidate passes the same dequeue-time admission
        as pad-alone dispatch."""
        pol = self.batch_policy
        with self._model_lock:
            pred = self._predictor
        buckets = pred.batch_buckets
        # the policy's plan: target bucket + idle-wait budget. An
        # SLO-aware policy (slo_queue_threshold) stops at a SMALL
        # bucket with zero idle wait at low load — p50 at low QPS no
        # longer pays the full-bucket hold; saturated plans are the
        # legacy largest-bucket fill, unchanged
        if hasattr(pol, "plan"):
            max_rows, wait_ms = pol.plan(self._queue.qsize(), first.n,
                                         buckets)
        else:  # duck-typed policy without the SLO planner
            max_rows, wait_ms = buckets[-1], pol.max_wait_ms
        group = [first]
        total = first.n
        key = _batching.nonbatched_key(first.feed, pred.feed_names,
                                       pred.batched_feeds)
        hold_until = first.submitted + wait_ms / 1e3
        while total < max_rows and not self._stop.is_set():
            if pol.max_requests is not None and \
                    len(group) >= pol.max_requests:
                break
            limit = hold_until
            for r in group:
                if r.deadline is not None:
                    limit = min(limit, r.deadline)
            wait = limit - time.monotonic()
            try:
                cand = (self._queue.get_nowait() if wait <= 0
                        else self._queue.get(timeout=min(wait, 0.02)))
            except _queue.Empty:
                if wait <= 0:
                    break
                continue
            cand = self._admit(cand)
            if cand is None:
                continue
            if total + cand.n > max_rows or _batching.nonbatched_key(
                    cand.feed, pred.feed_names,
                    pred.batched_feeds) != key:
                w.carry.append(cand)
                break
            group.append(cand)
            total += cand.n
        return group

    def _worker_loop(self, w: _Worker) -> None:
        clone = None
        gen = 0
        while not self._stop.is_set() and not w.abandoned:
            if w.carry:
                req = w.carry.pop(0)
            else:
                try:
                    req = self._queue.get(timeout=0.05)
                except _queue.Empty:
                    continue
            req = self._admit(req)
            if req is None:
                continue
            group = ([req] if self.batch_policy is None
                     else self._coalesce(w, req))
            with self._model_lock:
                pred, gen_now = self._predictor, self._generation
            total = sum(r.n for r in group)
            bucket = (req.bucket if len(group) == 1
                      else _batching.pick_bucket(total, pred.batch_buckets))
            spans = _batching.row_spans(group)
            w.request = req
            w.group = group
            w.busy_since = now = time.monotonic()
            for (off, n), r in zip(spans, group):
                extra = ({"coalesced": len(group), "row": off}
                         if len(group) > 1 else {})
                self.journal.emit("serving.dispatch", span=r.span,
                                  inst=self.telemetry_inst, worker=w.index,
                                  n=n, bucket=bucket,
                                  queued_s=round(now - r.submitted, 6),
                                  **extra)
            try:
                if clone is None or gen != gen_now:
                    clone = pred.clone()
                    gen = gen_now
                feed = (self._pad(pred, req) if len(group) == 1
                        else _batching.merge_feeds(group, pred.feed_names,
                                                   pred.batched_feeds,
                                                   bucket))
                out = clone.run(feed)
                _block_on(out)
            except BaseException as e:
                for r in group:
                    first = self._complete(r, error=e)
                    # an ABANDONED worker's eventual outcome is stale
                    # evidence: the watchdog already tripped for the
                    # hang, and a late failure must not re-open a
                    # breaker that has since recovered (nor
                    # double-count into the metrics — _complete
                    # returning False means the watchdog won)
                    if not w.abandoned:
                        self.breaker.record(r.token, success=False)
                    if first:
                        self.metrics.bump("errors")
                        self.journal.emit(
                            "serving.error", span=r.span,
                            inst=self.telemetry_inst, worker=w.index,
                            error=f"{type(e).__name__}: {e}"[:300])
            else:
                if len(group) > 1:
                    self.metrics.bump("coalesced_batches")
                    self.metrics.bump("coalesced_requests", by=len(group))
                done_t = time.monotonic()
                for (off, n), r in zip(spans, group):
                    if not w.abandoned:
                        self.breaker.record(r.token, success=True)
                    sliced = _batching.slice_rows(out, off, n, bucket)
                    if self._complete(r, value=sliced):
                        latency = done_t - r.submitted
                        self.metrics.bump("completed")
                        self.metrics.record_latency(latency)
                        extra = ({"coalesced": len(group)}
                                 if len(group) > 1 else {})
                        self.journal.emit("serving.complete", span=r.span,
                                          inst=self.telemetry_inst,
                                          worker=w.index,
                                          latency_s=round(latency, 6),
                                          **extra)
            finally:
                w.busy_since = None
                w.request = None
                w.group = None
        # loop exit with requests still carried (stop flag raced the
        # coalescer): they were never dispatched — fail them typed so
        # no client blocks forever, probe tokens go back
        for r in w.carry:
            self.breaker.cancel(r.token)
            self._complete(r, error=ServerClosed("server stopping"))
        w.carry = []

    @staticmethod
    def _pad(predictor, req: _Request) -> Dict[str, Any]:
        """Pad batched feeds up to the precompiled bucket (zeros — the
        pad rows are sliced off the outputs)."""
        if req.n == req.bucket:
            return req.feed
        out = {}
        for k in predictor.feed_names:
            v = np.asarray(req.feed[k])
            if k in predictor.batched_feeds:
                pad = np.zeros((req.bucket - req.n,) + v.shape[1:], v.dtype)
                v = np.concatenate([v, pad], axis=0)
            out[k] = v
        return out

    def _complete(self, req: _Request, value=None,
                  error: Optional[BaseException] = None) -> bool:
        """First completion wins — atomically: the watchdog and a
        just-finishing worker may race to complete the same request, and
        a torn check-then-set would let the loser overwrite the winner's
        outcome (or double-count it in the metrics)."""
        with self._complete_lock:
            if req.done.is_set():
                return False
            req.error = error
            req.value = value
            req.completed = time.monotonic()
            req.done.set()
            return True

    def _watchdog_loop(self) -> None:
        interval = max(0.01, min(0.5, (self.watchdog_timeout or 1.0) / 4))
        while not self._stop.is_set():
            time.sleep(interval)
            now = time.monotonic()
            for w in list(self._workers):
                busy = w.busy_since
                if w.abandoned or busy is None:
                    continue
                if now - busy <= self.watchdog_timeout:
                    continue
                group = list(w.group or ())
                if not group and w.request is not None:
                    group = [w.request]
                w.abandoned = True
                self.metrics.bump("hangs")
                span = group[0].span if group else None
                # the hang event goes into the ring BEFORE the breaker
                # trips, so both this dump and the trip's are complete
                self.journal.emit("serving.hang", span=span,
                                  inst=self.telemetry_inst,
                                  worker=w.index,
                                  busy_s=round(now - busy, 6),
                                  inflight=len(group))
                self.breaker.trip()
                from .telemetry import flight_dump
                flight_dump("worker_hung", span=span,
                            detail={"worker": w.index,
                                    "busy_s": round(now - busy, 6),
                                    "watchdog_timeout":
                                        self.watchdog_timeout,
                                    "inst": self.telemetry_inst})
                _log().error(
                    "worker %d hung for %.2fs (watchdog_timeout=%.2fs): "
                    "breaker tripped, worker abandoned + replaced",
                    w.index, now - busy, self.watchdog_timeout)
                # EVERY request of a coalesced dispatch hung with it:
                # fail each fast (their callers are all waiting)
                for r in group:
                    self._complete(r, error=WorkerHung(
                        f"dispatch exceeded the {self.watchdog_timeout}s "
                        "watchdog timeout"))
                self.metrics.bump("workers_replaced")
                neww = self._spawn_worker(len(self._workers))
                # never-dispatched requests the coalescer carried on
                # the wedged worker move to its replacement — they
                # must not strand behind an abandoned loop
                neww.carry, w.carry = w.carry, []

    def _on_breaker_trip(self, reason: str) -> None:
        """Breaker (re)open: journal it and flight-record the recent
        ring. The watchdog's ``hang`` path already dumped WITH the
        hung request's span — don't double-dump for the same event;
        ``probe_failure`` re-opens are journal-only (the original trip
        dumped)."""
        self.journal.emit("serving.breaker_open", inst=self.telemetry_inst,
                          reason=reason, trips=self.breaker.trips)
        if reason == "failures":
            from .telemetry import flight_dump
            flight_dump("breaker_trip",
                        detail={"reason": reason,
                                "trips": self.breaker.trips,
                                "inst": self.telemetry_inst})

    # -- hot reload ----------------------------------------------------------

    def reload(self, dirname: str, block: bool = True):
        """Hot-swap the served model from a ``save_inference_model``
        artifact. The load (manifest CRC validation + AOT compile) and
        the golden-feed canary run OFF the request path on a dedicated
        thread; only the final pointer swap takes the model lock, so
        in-flight requests finish on the clone they started with — zero
        drops either way. Any failure (torn artifact →
        ``CheckpointCorrupt``, signature drift or canary rejection →
        :class:`ReloadFailed`) leaves the previous model serving.

        ``block=False`` returns the loader thread immediately
        (``last_reload_error`` and the metrics counters carry the
        outcome); ``block=True`` joins and re-raises."""
        err: List[BaseException] = []

        def _load():
            try:
                self._do_reload(dirname)
            except BaseException as e:
                err.append(e)

        t = threading.Thread(target=_load, daemon=True,
                             name="pdtpu-serving-reload")
        t.start()
        if not block:
            return t
        t.join()
        if err:
            raise err[0]
        return None

    def reload_preflight(self, dirname: str):
        """Static pre-reload contract check: the
        :class:`~paddle_tpu.analysis.LintReport` of
        ``analysis.contracts.check_reload_compat`` for swapping the
        artifact at ``dirname`` in over the currently-served model —
        metadata only (no CRC pass, no deserialization, no AOT
        compile), so an operator or a rolling-fleet controller can
        vet a candidate against every server BEFORE any of them pays
        a load. ``reload`` runs this automatically and rejects on any
        error-severity finding."""
        from .analysis import contracts
        info = self._io.read_artifact_meta(dirname)
        with self._model_lock:
            served = contracts.serving_spec(self._predictor)
        return contracts.check_reload_compat(served, info)

    def _reload_static_check(self, dirname: str) -> None:
        # early REJECT only, never an early accept: a candidate whose
        # metadata alone proves the swap would strand in-flight traffic
        # fails before the load + per-bucket AOT compile is paid; an
        # unreadable/odd artifact falls through for the real load to
        # classify (CheckpointCorrupt with the CRC detail), and the
        # post-load checks below stay as the backstop for drift classes
        # only the deserialized export shows
        try:
            report = self.reload_preflight(dirname)
        except Exception:
            return
        errs = report.at_least("error")
        if errs:
            more = (f" (+{len(errs) - 1} more static contract finding(s))"
                    if len(errs) > 1 else "")
            raise ReloadFailed(dirname, errs[0].message + more)

    def _do_reload(self, dirname: str) -> None:
        with self._reload_lock:
            try:
                self._reload_static_check(dirname)
                new_pred = self._io.load_inference_model(dirname)
                old = self._predictor
                if list(new_pred.feed_names) != list(old.feed_names):
                    raise ReloadFailed(
                        dirname, f"feed names {new_pred.feed_names} != "
                        f"served model's {old.feed_names}")
                dropped = [b for b in old.batch_buckets
                           if b not in new_pred.batch_buckets]
                if dropped:
                    raise ReloadFailed(
                        dirname, f"bucket set shrank (missing {dropped}): "
                        "in-flight bucket traffic would go off-bucket")
                for b in old.batch_buckets:
                    got, want = new_pred.feed_spec(b), old.feed_spec(b)
                    if got != want:
                        diff = sorted(k for k in want if got.get(k) != want[k])
                        raise ReloadFailed(
                            dirname, f"feed signature drifted at bucket {b} "
                            f"(fields {diff}: {[got.get(k) for k in diff]} vs "
                            f"served {[want[k] for k in diff]}): queued "
                            "in-flight requests validated against the old "
                            "shapes would all fail on the new model")
                self._canary(new_pred, dirname)
                # candidate buckets are already AOT-compiled: warm them
                # off-thread so the swap doesn't cold-start a request
                self._warmup(new_pred)
            except BaseException as e:
                self._last_reload_error = e
                self.metrics.bump("reload_failures")
                self.journal.emit("serving.reload", inst=self.telemetry_inst,
                                  dirname=dirname, ok=False,
                                  error=f"{type(e).__name__}: {e}"[:300])
                # the rejected candidate's AOT compiles happened OFF the
                # request path: re-pin so the compiles_since_warmup
                # contract signal doesn't read as a (false) request-path
                # recompile forever after a rolled-back reload
                if self._pinned_compiles is not None:
                    self._pinned_compiles = self._io.aot_compile_count()
                _log().warning("hot reload of %s rolled back: %s", dirname, e)
                raise
            with self._model_lock:
                self._predictor = new_pred
                self._generation += 1
            self._last_reload_error = None
            self._pinned_compiles = self._io.aot_compile_count()
            self.metrics.bump("reloads")
            self.journal.emit("serving.reload", inst=self.telemetry_inst,
                              dirname=dirname, ok=True,
                              generation=self._generation)
            _log().info("hot reload: now serving %s (generation %d)",
                        dirname, self._generation)

    def _canary(self, predictor, dirname: str) -> None:
        # the golden feed is resized onto a precompiled bucket exactly
        # like warmup does (Predictor.run is exact-bucket-strict, and a
        # legal off-bucket golden feed must not make every reload fail)
        buckets = predictor.batch_buckets
        n = 0
        for k in sorted(predictor.batched_feeds):
            if self.golden_feed is not None and k in self.golden_feed:
                n = int(np.asarray(self.golden_feed[k]).shape[0])
                break
        fits = [b for b in buckets if b >= n]
        feed = self._bucket_feed(predictor, fits[0] if fits else buckets[-1])
        try:
            out = predictor.run(feed)
            _block_on(out)
        except Exception as e:
            raise ReloadFailed(
                dirname, f"canary execution failed: {type(e).__name__}: {e}")
        bad = _nonfinite_outputs(out)
        if bad:
            raise ReloadFailed(
                dirname, f"canary produced non-finite outputs: {bad}")
        if self.canary_check is not None:
            try:
                ok = self.canary_check(out)
            except Exception as e:
                raise ReloadFailed(dirname, f"canary_check raised "
                                   f"{type(e).__name__}: {e}")
            if ok is False:
                raise ReloadFailed(dirname, "canary_check returned False")

    # -- observability -------------------------------------------------------

    @property
    def generation(self) -> int:
        with self._model_lock:
            return self._generation

    @property
    def last_reload_error(self) -> Optional[BaseException]:
        """The most recent reload's failure (None after a success) —
        the outcome channel for ``reload(..., block=False)`` callers."""
        return self._last_reload_error

    def _alive_workers(self) -> List[_Worker]:
        """THE worker-liveness definition — shared by :meth:`health`
        and the registry collector so ``/healthz`` and the
        ``paddle_tpu_serving_workers*`` gauges can never drift."""
        return [w for w in self._workers
                if not w.abandoned and w.thread is not None
                and w.thread.is_alive()]

    def health(self) -> Dict[str, Any]:
        """Readiness/liveness state machine: ``live`` (the process can
        still make progress — workers exist and the runtime is not
        stopped) and ``ready`` (new requests are being accepted AND have
        a worker pool behind them). States: ``starting`` → ``ready``
        (sub-states ``overloaded`` while the queue is full and
        ``breaker_open``/``half_open`` while tripped) → ``draining`` →
        ``stopped``."""
        with self._state_lock:
            state = self._state
        if state == "ready":
            bstate = self.breaker.state
            if bstate == "open":
                state = "breaker_open"
            elif bstate == "half_open":
                state = "half_open"
            elif self._queue.full():
                state = "overloaded"
        alive = self._alive_workers()
        return {
            "live": state not in ("stopped",) and bool(alive),
            "ready": state in ("ready", "overloaded", "half_open"),
            "state": state,
            "generation": self.generation,
            "queue_depth": self._queue.qsize(),
            "queue_capacity": self.queue_size,
            "workers": len(alive),
            "workers_busy": sum(1 for w in alive if w.busy_since is not None),
            "breaker": self.breaker.state,
            "uptime_s": round(time.monotonic() - self._started_at, 3),
        }

    def repin_compiles(self) -> None:
        """Re-pin the ``compiles_since_warmup`` contract counter. The
        AOT counter is process-wide, so it also moves when ANOTHER
        server in the process legitimately loads off the request path —
        a fleet sibling's rolling reload or a router ``replace()``. The
        owner of that operation re-pins the rest of the fleet
        (``FleetRouter`` does this automatically) so the signal keeps
        meaning "request-path recompiles on THIS server". No-op before
        warmup."""
        if self._pinned_compiles is not None:
            self._pinned_compiles = self._io.aot_compile_count()

    def telemetry_families(self):
        """This server's FULL registry export — every
        ``ServingMetrics`` counter + the latency histogram (same store
        ``report()`` reads, so the series can never disagree) plus live
        queue-depth/capacity/worker gauges and breaker/generation
        state. Doubles as the process-registry collector callback
        (called at scrape time) and the per-replica source a
        :class:`~paddle_tpu.fleet.FleetRouter` merges under a
        ``replica`` label for the fleet-aggregated ``/metrics``."""
        from .telemetry.registry import counter_family, gauge_family

        inst = self.telemetry_inst
        labels = {"inst": inst}
        fams = self.metrics.telemetry_families(inst)
        alive = self._alive_workers()
        bstate = self.breaker.state
        fams.extend([
            gauge_family("paddle_tpu_serving_queue_depth",
                         "Requests currently queued",
                         [(labels, self._queue.qsize())]),
            gauge_family("paddle_tpu_serving_queue_capacity",
                         "Bounded queue capacity",
                         [(labels, self.queue_size)]),
            gauge_family("paddle_tpu_serving_workers",
                         "Live (non-abandoned) workers",
                         [(labels, len(alive))]),
            gauge_family("paddle_tpu_serving_workers_busy",
                         "Workers currently executing a dispatch",
                         [(labels, sum(1 for w in alive
                                       if w.busy_since is not None))]),
            gauge_family("paddle_tpu_serving_breaker_open",
                         "1 while the circuit breaker is open",
                         [(labels, 1 if bstate == "open" else 0)]),
            gauge_family("paddle_tpu_serving_breaker_half_open",
                         "1 while the breaker awaits its half-open probe",
                         [(labels, 1 if bstate == "half_open" else 0)]),
            counter_family("paddle_tpu_serving_breaker_trips_total",
                           "Circuit-breaker trips",
                           [(labels, self.breaker.trips)]),
            gauge_family("paddle_tpu_serving_generation",
                         "Served-model generation (bumps on hot reload)",
                         [(labels, self.generation)]),
        ])
        return fams

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1"):
        """Opt-in scrape endpoint: start the stdlib ``GET /metrics``
        (Prometheus text of the process registry — this server's
        series carry its ``inst`` label) + ``GET /healthz`` (this
        server's :meth:`health`; 503 once not live) server. Port 0
        picks a free port (``.port``); :meth:`close` stops it. The
        same :class:`~paddle_tpu.telemetry.TelemetryServer` backs
        ``Trainer.serve_metrics`` — one scraper config covers the
        trainer and the serving fleet."""
        from .telemetry import serve_metrics as _serve

        if self._telemetry_server is None:
            self._telemetry_server = _serve(health_fn=self.health,
                                            port=port, host=host)
        return self._telemetry_server

    def ship_to(self, addr, origin=None, **kw):
        """Attach the PROCESS telemetry shipper to a collector at
        ``addr`` — journal events + registry snapshots stream there in
        the background (``PDTPU_TELEMETRY_ADDR`` does the same with
        zero code, including inside spawned replica processes).
        Returns the :class:`~paddle_tpu.telemetry.shipper.Shipper`."""
        from .telemetry.shipper import ship_to as _ship_to

        return _ship_to(addr, origin=origin, **kw)

    def report(self) -> Dict[str, Any]:
        """Metrics + health in one dict (the serving mirror of
        ``Trainer.pipeline_report()``): latency percentiles, queue
        depth, reject/timeout/error/breaker counters, reload outcomes,
        and the compile-count pin (``compiles_since_warmup`` must stay 0
        for a bucketed server — the AOT-once serving contract)."""
        out = self.metrics.snapshot()
        out["health"] = self.health()
        out["breaker"] = {"state": self.breaker.state,
                          "trips": self.breaker.trips}
        with self._model_lock:
            pred = self._predictor
        compiles = self._io.aot_compile_count()
        out["batch_buckets"] = list(pred.batch_buckets)
        out["compiles_since_warmup"] = (
            None if self._pinned_compiles is None
            else compiles - self._pinned_compiles)
        return out


# -- helpers ------------------------------------------------------------------


def _register_server_telemetry(server: PredictorServer) -> int:
    """Register the server's scrape-time collector in the process
    registry — the callback IS :meth:`PredictorServer.
    telemetry_families` (one export surface for the process registry
    AND fleet aggregation, so they can never drift). Weakly bound — a
    collected server's series drop out, and :meth:`PredictorServer.
    close`/:meth:`~PredictorServer.kill` remove the collector eagerly
    so a stopped-but-referenced server stops exporting live-looking
    gauges."""
    from .telemetry import get_registry

    return get_registry().add_collector(PredictorServer.telemetry_families,
                                        owner=server)


def _block_on(out) -> None:
    import jax

    jax.block_until_ready(out)


def _check_finite(feed: Dict[str, Any], feed_names) -> None:
    for k in feed_names:
        v = np.asarray(feed[k])
        if v.dtype.kind == "f" and not np.isfinite(v).all():
            raise InvalidRequest(k, "contains non-finite values "
                                 "(NaN/Inf payload rejected)")


def _nonfinite_outputs(out) -> List[str]:
    bad = []
    items = out.items() if isinstance(out, dict) else [("output", out)]
    for k, v in items:
        a = np.asarray(v)
        if a.dtype.kind == "f" and not np.isfinite(a).all():
            bad.append(str(k))
    return bad


__all__ = [
    "BreakerPolicy", "CircuitBreaker", "CircuitOpen", "DeadlineExceeded",
    "InvalidRequest", "LatencyHistogram", "PendingResult", "PredictorServer",
    "ReloadFailed", "ReplicaDied", "ServerClosed", "ServerOverloaded",
    "ServingError", "ServingMetrics", "WorkerHung",
]
