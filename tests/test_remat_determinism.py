"""Rematerialization (memory_optimize → per-block jax.checkpoint) and
the deterministic flag wiring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import debugger, optimizer as opt, transpiler
from paddle_tpu.models import transformer


def _feed(bs=4, seq=32, vocab=64):
    rng = np.random.RandomState(0)
    src = rng.randint(3, vocab, (bs, seq)).astype(np.int64)
    trg = np.zeros_like(src)
    trg[:, 0] = 1
    trg[:, 1:] = src[:, :-1]
    labels = np.concatenate([trg[:, 1:], np.full((bs, 1), 2)], axis=1).astype(np.int64)
    return {"src_ids": src, "trg_ids": trg, "labels": labels}


def _cfg(**kw):
    return transformer.base_config(src_vocab=64, trg_vocab=64, d_model=32,
                                   d_inner=128, num_heads=4, num_encoder_layers=3,
                                   num_decoder_layers=3, dropout=0.0, **kw)


def _trainer(strategy=None):
    prog = pt.build(transformer.make_model(_cfg()))
    return pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss", strategy=strategy,
                      donate=False)


@pytest.mark.slow
def test_memory_optimize_strategy_consumed_by_trainer():
    """The VERDICT 'phantom knob' check: memory_optimize() must actually
    change the compiled step. The Trainer's loss path must contain one
    remat (jax.checkpoint) region per transformer block when the
    strategy is applied, with identical numerics.

    The memory effect itself is hardware-dependent: XLA:CPU's scheduler
    ignores remat regions for buffer assignment, while on a real TPU
    chip this exact model measures 552 MB -> 49 MB of temp buffers
    (d_model=128 config, bs=16 seq=256; see
    test_remat_reduces_memory_on_tpu which asserts it when a TPU is
    present)."""
    feed = _feed()
    plain = _trainer()
    plain.startup(sample_feed=feed)
    remat = _trainer(strategy=transpiler.memory_optimize())
    remat.startup(sample_feed=feed)
    # same init seed -> identical params; identical numerics either way
    l0 = float(plain.step(feed)["loss"])
    l1 = float(remat.step(feed)["loss"])
    assert l1 == pytest.approx(l0, rel=1e-5)

    def jaxpr_of(tr):
        return str(jax.make_jaxpr(
            lambda p: tr._loss_and_aux(p, tr.scope.state, jax.random.PRNGKey(0),
                                       tr._put_feed(feed))[0])(tr.scope.params))

    assert "remat" not in jaxpr_of(plain)
    n_blocks = 3 + 3  # encoder + decoder layers in _cfg()
    assert jaxpr_of(remat).count("remat2") >= n_blocks


@pytest.mark.skipif(jax.devices()[0].platform != "tpu",
                    reason="XLA:CPU buffer assignment ignores remat regions")
def test_remat_reduces_memory_on_tpu():
    """Needs an activation-dominated config — below ~1MB of temps the TPU
    buffer assignment reports 0 for everything. At this config the chip
    measures ~550 MB plain vs ~50 MB remat (verified on v5e)."""
    feed = _feed(bs=16, seq=256)

    def trainer(strategy=None):
        cfg = transformer.base_config(
            src_vocab=64, trg_vocab=64, d_model=128, d_inner=1024, num_heads=4,
            num_encoder_layers=6, num_decoder_layers=6, dropout=0.0)
        prog = pt.build(transformer.make_model(cfg))
        return pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss",
                          strategy=strategy, donate=False)

    plain = trainer()
    plain.startup(sample_feed=feed)
    remat = trainer(strategy=transpiler.memory_optimize())
    remat.startup(sample_feed=feed)
    m_plain = debugger.compiled_memory_usage(plain, feed)
    m_remat = debugger.compiled_memory_usage(remat, feed)
    assert m_remat["temp_mb"] < 0.5 * m_plain["temp_mb"], (m_plain, m_remat)


@pytest.mark.slow
def test_model_config_remat_equivalent_numerics():
    feed = _feed()
    p0 = pt.build(transformer.make_model(_cfg()))
    p1 = pt.build(transformer.make_model(_cfg(remat=True)))
    params, state = p0.init(jax.random.PRNGKey(0), **feed)
    out0, _ = jax.jit(p0.apply)(params, state, **feed)
    out1, _ = jax.jit(p1.apply)(params, state, **feed)
    np.testing.assert_allclose(float(out0["loss"]), float(out1["loss"]), rtol=1e-6)
    # grads agree too (checkpoint recompute is exact)
    g0 = jax.grad(lambda p: p0.apply(p, state, **feed)[0]["loss"])(params)
    g1 = jax.grad(lambda p: p1.apply(p, state, **feed)[0]["loss"])(params)
    for k in g0:
        np.testing.assert_allclose(np.asarray(g0[k]), np.asarray(g1[k]),
                                   atol=1e-5, rtol=1e-4, err_msg=k)


@pytest.mark.slow
def test_bert_remat_flag():
    from paddle_tpu.models import bert

    cfg = bert.base_config(vocab_size=64, max_len=32, d_model=32, d_inner=64,
                           num_heads=4, num_layers=2, dropout=0.0, remat=True)
    prog = pt.build(bert.make_pretrain_model(cfg))
    rng = np.random.RandomState(0)
    feed = {"input_ids": rng.randint(0, 64, (2, 16)).astype(np.int64),
            "token_type_ids": np.zeros((2, 16), np.int64),
            "mlm_positions": rng.randint(0, 16, (2, 3)).astype(np.int64),
            "mlm_labels": rng.randint(0, 64, (2, 3)).astype(np.int64),
            "nsp_label": rng.randint(0, 2, (2,)).astype(np.int64)}
    params, state = prog.init(jax.random.PRNGKey(0), **feed)
    g = jax.grad(lambda p: prog.apply(p, state, **feed)[0]["loss"])(params)
    assert all(np.all(np.isfinite(np.asarray(v))) for v in g.values())


def test_deterministic_flag_wires_jax_config():
    from paddle_tpu.core import config as cfg

    old_prec = jax.config.jax_default_matmul_precision
    old_threefry = jax.config.jax_threefry_partitionable
    try:
        cfg.enable_determinism()
        assert jax.config.jax_default_matmul_precision == "highest"
        assert jax.config.jax_threefry_partitionable is True
        assert cfg.get_flag("deterministic") is True
        import os
        assert "--xla_gpu_deterministic_ops=true" in os.environ.get("XLA_FLAGS", "")
    finally:
        cfg.disable_determinism()
    # disable restores the pre-enable state, not a hardcoded one
    assert jax.config.jax_default_matmul_precision == old_prec
    assert jax.config.jax_threefry_partitionable == old_threefry
    assert cfg.get_flag("deterministic") is False


@pytest.fixture(scope="module")
def _no_remat_losses():
    feeds = [_feed() for _ in range(2)]
    ref = _trainer()
    ref.startup(sample_feed=feeds[0])
    return feeds, [float(ref.step(f)["loss"]) for f in feeds]


@pytest.mark.parametrize("policy", [
    "dots",
    pytest.param("dots_no_batch", marks=pytest.mark.slow),
    pytest.param("everything", marks=pytest.mark.slow),
])
def test_remat_policy_numerics_unchanged(policy, _no_remat_losses):
    """Checkpoint policies change WHAT is saved (memory/recompute), not
    the computed values: per-step losses must equal the no-remat run."""
    from paddle_tpu.parallel import DistStrategy

    feeds, ref_losses = _no_remat_losses
    tr = _trainer(DistStrategy(remat=True, remat_policy=policy))
    tr.startup(sample_feed=feeds[0])
    losses = [float(tr.step(f)["loss"]) for f in feeds]
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)


def test_remat_policy_unknown_name_rejected():
    from paddle_tpu.framework import resolve_remat_policy
    with pytest.raises(Exception, match="unknown remat policy"):
        resolve_remat_policy("keep_the_good_bits")
    assert resolve_remat_policy(None) is None
    assert resolve_remat_policy("dots") is jax.checkpoint_policies.dots_saveable
    fn = lambda *a, **k: False  # noqa: E731
    assert resolve_remat_policy(fn) is fn
