"""Layer-function generation helpers.

Analog of python/paddle/fluid/layers/layer_function_generator.py, whose
``__all__`` ({generate_layer_fn, generate_layer_fn_noattr, autodoc,
templatedoc, deprecated}) is part of the public ``fluid.layers``
namespace. The reference generates Python wrappers from C++ OpProtos
(get_all_op_protos, pybind.cc:407); here the "op registry" is the set
of jnp/lax-backed layer functions across the layers submodules, so
generation is a lookup that returns the already-idiomatic function.
"""

from __future__ import annotations

import functools
import warnings
from typing import Callable, Optional

__all__ = ["deprecated", "generate_layer_fn", "generate_layer_fn_noattr",
           "autodoc", "templatedoc"]


def _registry_modules():
    from . import control_flow, detection, nn, ops, sequence, tensor

    return (ops, nn, tensor, sequence, control_flow, detection)


def generate_layer_fn(op_type: str) -> Callable:
    """Return the layer function registered under ``op_type``
    (layer_function_generator.py generate_layer_fn analog — the OpProto
    walk collapses to a module lookup)."""
    import inspect

    from ..core.errors import NotFoundError

    for mod in _registry_modules():
        fn = getattr(mod, op_type, None)
        # only functions DEFINED in a layers module count as registered
        # ops — imported helpers (enforce, LayerHelper, jnp…) must not
        # resolve, or a typo'd op name silently returns a non-layer
        if (inspect.isfunction(fn)
                and getattr(fn, "__module__", "").startswith("paddle_tpu.layers")):
            return fn
    raise NotFoundError(f"no layer function registered for op {op_type!r}")


def generate_layer_fn_noattr(op_type: str) -> Callable:
    """Same lookup for attr-less activation-style ops."""
    return generate_layer_fn(op_type)


def autodoc(comment: str = "") -> Callable:
    """Docstring decorator (autodoc analog): prepend ``comment`` to the
    function's docstring."""
    def decorator(func):
        func.__doc__ = comment + (func.__doc__ or "")
        return func
    return decorator


def templatedoc(op_type: Optional[str] = None) -> Callable:
    """templatedoc analog. The reference substitutes ${comment} fields
    from the OpProto; here docstrings are authored directly, so this
    simply tags the function with its op type."""
    def decorator(func):
        func.__doc__ = (func.__doc__ or "").strip()
        func._op_type = op_type or func.__name__
        return func
    return decorator


def deprecated(since: str = "", instead: str = "") -> Callable:
    """Mark a layer deprecated; warns once per call site like the
    reference's annotations.deprecated."""
    def decorator(func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            warnings.warn(
                f"{func.__name__} is deprecated"
                + (f" since {since}" if since else "")
                + (f"; use {instead} instead" if instead else ""),
                DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)
        return wrapper
    return decorator
