"""Structured run journal: one correlated JSONL event stream per
process.

Every event carries the process ``run`` id, a monotonic ``seq``, a
wall-clock ``t``, a ``kind`` (dotted ``subsystem.event``), and an
optional ``span`` — the trace id minted at ``submit``/dispatch time
and propagated through feeder fill, fused-dispatch chunks, serving
worker execution, and the async-PS wire protocol, so one slow request
or lost push is attributable end to end (``tools/flight_dump.py
--span <id>`` renders exactly its lifecycle).

The journal always retains a bounded ring of recent events — the
flight recorder's buffer (:mod:`paddle_tpu.telemetry.recorder` flushes
it to disk on crash-shaped triggers). A JSONL file sink is opt-in
(:meth:`RunJournal.open`, or ``PDTPU_JOURNAL_PATH`` for the process
default): the hot path then pays one ``json.dumps`` + buffered write
per event, which is why dispatch-rate emitters stay ring-only by
default.

Emitting is cheap by construction (dict build + lock + deque append,
no device interaction): the trainer emits once per DISPATCH (not per
step), which keeps journal overhead inside the <2% K=16 budget the
tests pin, with zero added device↔host syncs.

At very high serving QPS even the ring fills with request-lifecycle
events faster than anything else can land in it. **Per-kind sampling**
(``RunJournal(sample={"serving": 0.01})``, or
``PDTPU_JOURNAL_SAMPLE=serving=0.01,ps=0.5`` for the process default)
keeps a deterministic fraction: the keep/drop decision is a hash of
the event's **span** (so one request's submit → dispatch → complete
events share a fate — a sampled-in submit always keeps its lifecycle)
or of the event's seq for span-less events. No ``random`` anywhere:
the same traffic journals the same events every run. Kinds match by
longest dotted prefix (``"serving"`` covers every ``serving.*`` kind;
``"*"`` is the catch-all); unconfigured kinds always keep. Dropped
events still consume a ``seq`` (gaps in the sink are visible sampling,
not corruption) and are counted in ``dropped_sampled``.
"""

from __future__ import annotations

import json
import os
import secrets
import threading
import time
import zlib
from collections import deque
from typing import Any, Dict, List, Optional

# ring capacity: enough context to explain the seconds before a crash
# without a week-long fit growing memory (one event is ~200 bytes)
DEFAULT_RING = 4096


def new_run_id() -> str:
    """Process run id: wall-clock prefix (sortable across a fleet's
    dumps) + random suffix (unique across same-second restarts)."""
    return time.strftime("%Y%m%dT%H%M%S") + "-" + secrets.token_hex(4)


# span ids are minted on hot paths (one per dispatch chunk / serving
# request); os.urandom per mint costs tens of µs on some kernels, so
# spans are a per-process random prefix (urandom, once) + a counter —
# unique within the process by construction, unique across a fleet's
# processes by the 32-bit prefix
_span_lock = threading.Lock()
_span_prefix = secrets.token_hex(4)
_span_counter = 0


def _mint_span() -> str:
    global _span_counter
    with _span_lock:
        _span_counter += 1
        n = _span_counter
    return f"{_span_prefix}{n & 0xFFFFFFFF:08x}"


class RunJournal:
    """Thread-safe correlated event stream (ring + optional sinks)."""

    def __init__(self, run_id: Optional[str] = None,
                 ring_size: int = DEFAULT_RING,
                 sample: Optional[Dict[str, float]] = None):
        self.run_id = run_id or new_run_id()
        self._lock = threading.Lock()
        self._seq = 0
        self._ring: deque = deque(maxlen=ring_size)
        self._files: List[Any] = []
        self._sample: Dict[str, float] = dict(sample or {})
        self._subscribers: Dict[int, Any] = {}
        self._next_sub = 0
        self.dropped_sink_writes = 0
        self.dropped_sampled = 0
        self.ingested = 0

    # -- spans -------------------------------------------------------------
    @staticmethod
    def new_span() -> str:
        """Mint a trace/span id (16 hex chars): at ``submit`` for a
        serving request, at chunk fill/dispatch for a training step,
        at ``step`` for an async-PS push batch. Cheap by construction
        (a counter under a process-random prefix, no urandom per
        call) — minting rides hot paths."""
        return _mint_span()

    # -- sampling ----------------------------------------------------------
    def set_sample(self, sample: Optional[Dict[str, float]]) -> None:
        """Replace the per-kind sampling table: ``{kind_prefix: rate}``
        with rates in [0, 1] (``{}``/None keeps everything). Matching
        is by longest dotted prefix of the event kind; ``"*"`` is the
        catch-all for otherwise-unconfigured kinds."""
        with self._lock:
            self._sample = dict(sample or {})

    def sample_rate(self, kind: str) -> float:
        """The configured keep-rate for ``kind`` (1.0 = keep all)."""
        with self._lock:
            return self._rate_locked(kind)

    def _rate_locked(self, kind: str) -> float:
        s = self._sample
        if not s:
            return 1.0
        k = kind
        while True:
            if k in s:
                return float(s[k])
            if "." not in k:
                break
            k = k.rsplit(".", 1)[0]
        return float(s.get("*", 1.0))

    @staticmethod
    def _sampled_in(key: str, rate: float) -> bool:
        # deterministic keep/drop: a crc32 of the span (or seq) mapped
        # onto [0, 1) — NOT random.random(), so the same traffic
        # journals the same events every run, and every event of one
        # span shares a fate (span-consistent sampling)
        if rate >= 1.0:
            return True
        if rate <= 0.0:
            return False
        return (zlib.crc32(key.encode()) & 0xFFFFFFFF) / 2.0 ** 32 < rate

    # -- sinks -------------------------------------------------------------
    def open(self, path: str) -> "RunJournal":
        """Attach a JSONL file sink (append mode, line-buffered via
        explicit flush per event). Multiple sinks are allowed."""
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        f = open(path, "a", encoding="utf-8")
        with self._lock:
            self._files.append(f)
        return self

    def close(self) -> None:
        with self._lock:
            files, self._files = self._files, []
        for f in files:
            try:
                f.close()
            except OSError:
                pass

    # -- subscribers -------------------------------------------------------
    def subscribe(self, fn) -> int:
        """Register a live-event callback: ``fn(event)`` is called for
        EVERY event — per-kind sampling does NOT apply (sampling is the
        ring/sink pressure valve; a subscriber is a live observation
        channel, and the fleet wire's ``DISPATCHED`` ordering hangs off
        it — a sampled-out ``serving.dispatch`` must still fire it).
        Called AFTER the ring append (or sampling drop) and OUTSIDE the
        journal lock — a subscriber may emit or read without
        deadlocking, at the cost of cross-thread callback ordering not
        being seq-strict; it runs on the EMITTER's thread, so keep it
        cheap and never let it block unboundedly. Exceptions are
        swallowed (telemetry never takes down the run it observes).
        Returns a handle for :meth:`unsubscribe`."""
        with self._lock:
            sid = self._next_sub
            self._next_sub += 1
            self._subscribers[sid] = fn
            return sid

    def unsubscribe(self, sid: int) -> None:
        with self._lock:
            self._subscribers.pop(sid, None)

    # -- emission ----------------------------------------------------------
    def emit(self, kind: str, span: Optional[str] = None,
             **fields) -> Dict[str, Any]:
        """Record one event; returns the event dict (already sequenced).
        The sink write happens UNDER the journal lock: concurrent
        emitters (serving workers, the watchdog, the feeder fill
        thread, the training loop) must neither interleave bytes
        mid-line nor land out of ``seq`` order in the JSONL file. A
        failing file sink is counted, never raised — telemetry must
        not take down the run it observes."""
        subs: List[Any] = []
        with self._lock:
            self._seq += 1
            event: Dict[str, Any] = {"run": self.run_id, "seq": self._seq,
                                     "t": time.time(), "kind": kind}
            if span is not None:
                event["span"] = span
            event.update(fields)
            if self._subscribers:
                subs = list(self._subscribers.values())
            rate = self._rate_locked(kind)
            sampled_out = rate < 1.0 and not self._sampled_in(
                span if span is not None else f"{self.run_id}:{self._seq}",
                rate)
            if sampled_out:
                # sampled out: the seq is consumed (sink gaps read as
                # sampling, not corruption) but neither ring nor sinks
                # see the event — the high-QPS pressure valve.
                # Subscribers still fire below: they are not a sink.
                self.dropped_sampled += 1
            else:
                self._ring.append(event)
                self._write_sinks_locked(event, kind)
        for fn in subs:
            try:
                fn(event)
            except Exception:
                pass
        return event

    def _write_sinks_locked(self, event: Dict[str, Any], kind: str) -> None:
        if not self._files:
            return
        try:
            line = json.dumps(event, sort_keys=True,
                              default=_json_default) + "\n"
        except (TypeError, ValueError):
            line = json.dumps(
                {"run": event.get("run", self.run_id), "seq": event["seq"],
                 "t": event["t"], "kind": kind,
                 "unserializable": True}) + "\n"
        for f in self._files:
            try:
                f.write(line)
                f.flush()
            except (OSError, ValueError):
                self.dropped_sink_writes += 1

    def ingest(self, events, origin: Optional[str] = None) -> int:
        """Absorb ANOTHER process's journal events into this one — the
        off-host shipping half of the cross-process fleet: a router
        pulls each remote replica's retained ring over the framed
        control link (``JOURNAL`` verb) and ingests it here, so one
        local ring (and one JSONL sink) holds the fleet-wide timeline.

        Shipped events keep their own ``run`` id and ``seq`` (the
        origin process's sequencing is the truth; this journal's
        ``seq`` is NOT consumed) and gain an ``origin`` field when one
        is given (the replica name). Spans correlate across processes
        by construction — the front door mints the span and the wire
        trace token hands it to the replica. Returns the number of
        events ingested."""
        n = 0
        with self._lock:
            for event in events:
                if not isinstance(event, dict) or "kind" not in event:
                    continue
                event = dict(event)
                if origin is not None:
                    event.setdefault("origin", origin)
                self._ring.append(event)
                self._write_sinks_locked(event, str(event["kind"]))
                n += 1
            self.ingested += n
        return n

    # -- reads -------------------------------------------------------------
    def recent(self, n: Optional[int] = None,
               kind: Optional[str] = None,
               span: Optional[str] = None) -> List[Dict[str, Any]]:
        """The retained ring (oldest first), optionally filtered by
        ``kind`` prefix and/or ``span``."""
        with self._lock:
            events = list(self._ring)
        if kind is not None:
            events = [e for e in events if e["kind"].startswith(kind)]
        if span is not None:
            events = [e for e in events if e.get("span") == span]
        if n is not None:
            events = events[-n:]
        return events

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq


def _json_default(o):
    try:
        import numpy as np
        if isinstance(o, np.generic):
            return o.item()
        if isinstance(o, np.ndarray):
            return o.tolist()
    except ImportError:
        pass
    return repr(o)


# -- the process-wide default journal -----------------------------------------

_default_lock = threading.Lock()
_default_journal: Optional[RunJournal] = None


def parse_sample(spec: Optional[str]) -> Dict[str, float]:
    """Parse a ``PDTPU_JOURNAL_SAMPLE`` value — comma-separated
    ``kind=rate`` pairs, e.g. ``"serving=0.01,ps=0.5"`` — into a
    sampling table. Malformed entries are skipped (a bad env var must
    not break startup); rates clamp to [0, 1]."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        kind, _, rate = part.partition("=")
        try:
            out[kind.strip()] = min(1.0, max(0.0, float(rate)))
        except ValueError:
            continue
    return out


def get_journal() -> RunJournal:
    """THE process journal (created on first use; honors
    ``PDTPU_JOURNAL_PATH`` as an initial JSONL sink and
    ``PDTPU_JOURNAL_SAMPLE`` as the initial per-kind sampling
    table)."""
    global _default_journal
    with _default_lock:
        if _default_journal is None:
            j = RunJournal(
                sample=parse_sample(os.environ.get("PDTPU_JOURNAL_SAMPLE")))
            path = os.environ.get("PDTPU_JOURNAL_PATH")
            if path:
                try:
                    j.open(path)
                except OSError:
                    pass  # an unwritable sink must not break startup
            _default_journal = j
        return _default_journal


def set_journal(journal: Optional[RunJournal]) -> Optional[RunJournal]:
    """Swap the process journal (tests; returns the previous one)."""
    global _default_journal
    with _default_lock:
        old, _default_journal = _default_journal, journal
        return old


__all__ = ["DEFAULT_RING", "RunJournal", "get_journal", "new_run_id",
           "parse_sample", "set_journal"]
