"""Global config / flag system.

Analog of the reference's three-tier flag system (SURVEY §5): gflags
read from env at import (python/paddle/fluid/__init__.py:112-133),
strategy objects, and build options. Here: a typed flag registry with
env-var override (``PDTPU_<NAME>``), plus dataclass strategy objects
living in paddle_tpu.parallel.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict


@dataclasses.dataclass
class _Flag:
    name: str
    default: Any
    parser: Callable[[str], Any]
    help: str
    value: Any = None


_REGISTRY: Dict[str, _Flag] = {}


def _parse_bool(s: str) -> bool:
    return s.lower() in ("1", "true", "yes", "on")


def define_flag(name: str, default: Any, help: str = "") -> None:
    if isinstance(default, bool):
        parser: Callable[[str], Any] = _parse_bool
    elif isinstance(default, int):
        parser = int
    elif isinstance(default, float):
        parser = float
    else:
        parser = str
    env = os.environ.get(f"PDTPU_{name.upper()}")
    value = parser(env) if env is not None else default
    _REGISTRY[name] = _Flag(name, default, parser, help, value)


def get_flag(name: str) -> Any:
    return _REGISTRY[name].value


def set_flag(name: str, value: Any) -> None:
    _REGISTRY[name].value = value


def flags() -> Dict[str, Any]:
    return {k: f.value for k, f in _REGISTRY.items()}


_determinism_saved: Dict[str, Any] = {}


def enable_determinism() -> None:
    """Wire the ``deterministic`` flag (FLAGS_cpu_deterministic analog)
    to real knobs: bitwise-reproducible matmul precision, the
    sharding-invariant threefry RNG, and XLA's deterministic-ops flag
    for any backend initialized after this call. Invoked automatically
    at package import when ``PDTPU_DETERMINISTIC=1``."""
    import jax

    if not _determinism_saved:
        _determinism_saved["matmul_precision"] = jax.config.jax_default_matmul_precision
        _determinism_saved["threefry"] = jax.config.jax_threefry_partitionable
        _determinism_saved["xla_flags"] = os.environ.get("XLA_FLAGS")
    jax.config.update("jax_default_matmul_precision", "highest")
    jax.config.update("jax_threefry_partitionable", True)
    xla_flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_gpu_deterministic_ops=false" in xla_flags:
        xla_flags = xla_flags.replace("--xla_gpu_deterministic_ops=false",
                                      "--xla_gpu_deterministic_ops=true")
        os.environ["XLA_FLAGS"] = xla_flags
    elif "--xla_gpu_deterministic_ops" not in xla_flags:
        os.environ["XLA_FLAGS"] = (xla_flags + " --xla_gpu_deterministic_ops=true").strip()
    set_flag("deterministic", True)


def disable_determinism() -> None:
    """Restore the jax-config state captured by :func:`enable_determinism`
    (the XLA env flag only affects backends not yet initialized)."""
    import jax

    if _determinism_saved:
        jax.config.update("jax_default_matmul_precision",
                          _determinism_saved.pop("matmul_precision"))
        jax.config.update("jax_threefry_partitionable",
                          _determinism_saved.pop("threefry"))
        old_xla = _determinism_saved.pop("xla_flags")
        if old_xla is None:
            os.environ.pop("XLA_FLAGS", None)
        else:
            os.environ["XLA_FLAGS"] = old_xla
    set_flag("deterministic", False)


# Core flags — counterparts of the whitelisted gflags the reference
# re-reads from env (fluid/__init__.py:112-133).
define_flag("check_nan_inf", False, "Scan op outputs for NaN/Inf (FLAGS_check_nan_inf analog)")
define_flag("benchmark", False, "Synchronize after each step and log timings (FLAGS_benchmark)")
define_flag("deterministic", False, "Force deterministic reductions (FLAGS_cpu_deterministic)")
define_flag("default_compute_dtype", "float32", "Compute dtype for layers ('bfloat16' on TPU for MXU)")
define_flag("seed", 0, "Global random seed (startup-program seed analog)")
define_flag("rng_impl", "auto",
            "PRNG key impl: auto|threefry2x32|rbg. 'auto' picks XLA's "
            "native RngBitGenerator on TPU (threefry synthesizes random "
            "bits from many VPU ops and can dominate dropout-heavy "
            "steps) and threefry elsewhere / under determinism")
define_flag("compile_cache_dir", "",
            "Persistent XLA compilation cache directory wired by "
            "Trainer.startup (empty = off). Repeated bench/CI runs skip "
            "recompiling the (fused) train step; hit/miss is logged on "
            "the first dispatch. Env PDTPU_COMPILE_CACHE_DIR")
define_flag("flash_block_q", 0,
            "flash-attention q-block rows; 0 = kernel default "
            "(ops/flash_attention.DEFAULT_BLOCK_Q). Env "
            "PDTPU_FLASH_BLOCK_Q lets an on-chip sweep winner "
            "(tools/flash_microbench.py) apply without a code edit")
define_flag("flash_block_k", 0,
            "flash-attention k-block rows; 0 = kernel default "
            "(see flash_block_q)")


def default_rng_impl() -> str:
    """Resolve the ``rng_impl`` flag. Determinism forces threefry: RBG
    bit-streams are backend/partitioning-dependent, threefry's are not
    (with jax_threefry_partitionable, see enable_determinism)."""
    impl = get_flag("rng_impl")
    if impl != "auto":
        return impl
    if get_flag("deterministic"):
        return "threefry2x32"
    import jax
    try:
        d = jax.devices()[0]
        desc = ((getattr(d, "platform", "") or "")
                + " " + (getattr(d, "device_kind", "") or "")).lower()
    except Exception:
        return "threefry2x32"
    return "rbg" if "tpu" in desc else "threefry2x32"


def make_prng_key(seed: int):
    """PRNGKey under the resolved ``rng_impl`` — the one key-construction
    point the executor/trainer path uses, so the whole step's dropout/
    init randomness follows the flag. TYPED keys (jax.random.key): a raw
    u32 key array loses its impl at the first jit boundary and gets
    reinterpreted as threefry; the typed dtype carries it."""
    import jax
    return jax.random.key(seed, impl=default_rng_impl())
