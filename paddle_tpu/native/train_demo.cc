// C++ training demo — the reference's python-free trainer entry
// (train/demo/demo_trainer.cc + train/test_train_recognize_digits.cc).
//
// The reference's demo loads a ProgramDesc and drives its C++ Executor.
// Our runtime is XLA/PJRT, whose only in-image entry point is the Python
// binding (no standalone PJRT C library ships here), so this binary
// embeds libpython *solely as the PJRT loader*: every piece of driver
// logic — synthetic data generation, RecordIO writing/scanning
// (native/recordio.cc, the same C API the ctypes binding uses),
// batching, the epoch loop, loss tracking, convergence check — is C++.
// The embedded interpreter is handed one fixed train-step callable and
// receives raw batch bytes.
//
// Build & run (see tests/test_train_demo.py):
//   g++ -O3 -std=c++17 train_demo.cc recordio.cc \
//       $(python3-config --includes) $(python3-config --embed --ldflags) \
//       -lz -o train_demo
//   JAX_PLATFORMS=cpu ./train_demo

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

// recordio C API (native/recordio.cc)
extern "C" {
void* rio_writer_open(const char* path, int compress, int chunk_bytes);
int rio_writer_write(void* handle, const uint8_t* data, uint32_t len);
int rio_writer_close(void* handle);
void* rio_scanner_open(const char* path);
int64_t rio_scanner_next(void* handle, const uint8_t** out);
void rio_scanner_close(void* handle);
}

namespace {

constexpr int kFeature = 64;   // compact mnist-like task: fast CPU jit
constexpr int kClasses = 10;
constexpr int kSamples = 1024;
constexpr int kBatch = 64;
constexpr int kEpochs = 4;

// deterministic LCG so the demo is reproducible without <random> seeding
// differences across libstdc++ versions
struct Lcg {
  uint64_t s;
  explicit Lcg(uint64_t seed) : s(seed) {}
  uint64_t next() { return s = s * 6364136223846793005ull + 1442695040888963407ull; }
  float unit() { return (next() >> 40) / float(1 << 24); }        // [0,1)
  float gauss() {  // sum of uniforms: cheap, good enough for a demo
    float a = 0;
    for (int i = 0; i < 4; ++i) a += unit();
    return (a - 2.0f) * 1.73f;
  }
};

struct Record {       // one sample: features then label
  float x[kFeature];
  int64_t y;
};

std::string WriteDataset(const char* path) {
  // class-dependent means -> linearly separable, so SGD provably learns
  Lcg centers_rng(7);
  std::vector<float> centers(kClasses * kFeature);
  for (auto& c : centers) c = centers_rng.gauss();

  void* w = rio_writer_open(path, /*compress=*/1, /*chunk_bytes=*/1 << 16);
  if (!w) return "rio_writer_open failed";
  Lcg noise(13);
  Record r;
  for (int i = 0; i < kSamples; ++i) {
    r.y = i % kClasses;
    for (int j = 0; j < kFeature; ++j)
      r.x[j] = centers[r.y * kFeature + j] + 0.5f * noise.gauss();
    if (rio_writer_write(w, reinterpret_cast<const uint8_t*>(&r), sizeof(r)) != 0)
      return "rio_writer_write failed";
  }
  if (rio_writer_close(w) != 0) return "rio_writer_close failed";
  return "";
}

// the only python the demo runs: build the model once, expose _step()
const char* kBootstrap = R"PY(
import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    # honor the env var even where a sitecustomize boot hook force-set
    # jax_platforms after env parsing (the axon transport would otherwise
    # be dialed — and block — despite JAX_PLATFORMS=cpu)
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np
import paddle_tpu as pt
from paddle_tpu import layers, optimizer as opt

_FEATURE, _CLASSES = 64, 10

def _net(image, label):
    h = layers.fc(image, 128, act="relu", name="fc1")
    logits = layers.fc(h, _CLASSES, name="fc2")
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    return {"loss": loss}

_prog = pt.build(_net)
_trainer = pt.Trainer(_prog, opt.SGD(0.1), loss_name="loss")
_started = False

def _step(batch_bytes, batch_size):
    global _started
    rec = np.frombuffer(batch_bytes, dtype=np.uint8).reshape(batch_size, -1)
    img = rec[:, :_FEATURE * 4].copy().view(np.float32)
    lab = rec[:, _FEATURE * 4:].copy().view(np.int64)
    feed = {"image": img, "label": lab}
    if not _started:
        _trainer.startup(sample_feed=feed)
        _started = True
    return float(_trainer.step(feed)["loss"])
)PY";

}  // namespace

struct FileGuard {  // remove the temp dataset on every exit path
  const char* path;
  ~FileGuard() { std::remove(path); }
};

int main() {
  // pid-tagged path so concurrent runs don't rewrite each other's data
  char data_path[128];
  std::snprintf(data_path, sizeof(data_path),
                "/tmp/paddle_tpu_train_demo.%d.recordio", (int)getpid());
  FileGuard guard{data_path};
  std::string err = WriteDataset(data_path);
  if (!err.empty()) {
    std::fprintf(stderr, "dataset: %s\n", err.c_str());
    return 1;
  }

  Py_Initialize();
  if (PyRun_SimpleString(kBootstrap) != 0) {
    std::fprintf(stderr, "bootstrap failed\n");
    return 1;
  }
  PyObject* main_mod = PyImport_AddModule("__main__");
  PyObject* step_fn = PyObject_GetAttrString(main_mod, "_step");
  if (!step_fn) {
    std::fprintf(stderr, "_step not found\n");
    return 1;
  }

  double first_epoch_loss = -1, last_epoch_loss = -1;
  std::vector<uint8_t> batch;
  for (int epoch = 0; epoch < kEpochs; ++epoch) {
    void* s = rio_scanner_open(data_path);
    if (!s) {
      std::fprintf(stderr, "rio_scanner_open failed\n");
      return 1;
    }
    double total = 0;
    int batches = 0, in_batch = 0;
    const uint8_t* rec = nullptr;
    int64_t n;
    batch.clear();
    while ((n = rio_scanner_next(s, &rec)) > 0) {
      if (n != sizeof(Record)) {
        std::fprintf(stderr, "bad record size %lld\n", (long long)n);
        return 1;
      }
      batch.insert(batch.end(), rec, rec + n);
      if (++in_batch == kBatch) {
        PyObject* res = PyObject_CallFunction(
            step_fn, "y#i", reinterpret_cast<const char*>(batch.data()),
            (Py_ssize_t)batch.size(), kBatch);
        if (!res) {
          PyErr_Print();
          return 1;
        }
        total += PyFloat_AsDouble(res);
        Py_DECREF(res);
        ++batches;
        in_batch = 0;
        batch.clear();
      }
    }
    rio_scanner_close(s);
    if (n == -2) {                 // recordio.cc: -1 = EOF, -2 = corruption
      std::fprintf(stderr, "recordio corruption in %s\n", data_path);
      return 1;
    }
    if (batches == 0) {
      std::fprintf(stderr, "no complete batches read\n");
      return 1;
    }
    double avg = total / batches;
    std::printf("epoch %d: avg_loss=%.4f (%d batches)\n", epoch, avg, batches);
    if (epoch == 0) first_epoch_loss = avg;
    last_epoch_loss = avg;
  }

  Py_DECREF(step_fn);
  Py_Finalize();

  if (last_epoch_loss < first_epoch_loss * 0.5) {
    std::printf("PASS: loss %.4f -> %.4f\n", first_epoch_loss, last_epoch_loss);
    return 0;
  }
  std::printf("FAIL: loss %.4f -> %.4f\n", first_epoch_loss, last_epoch_loss);
  return 2;
}
