"""Transformer (encoder-decoder, WMT en-de "base" config).

Capability analog of the reference's fluid transformer benchmark
(benchmark/fluid/models/machine_translation.py builds attention from
primitive ops; fluid has no attention kernels — SURVEY §5). Re-designed
TPU-first: pre-LN residual blocks, bf16-friendly, parameter names
aligned with parallel.transformer_tp_rules for TP/FSDP sharding, flash
attention switchable.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from .. import layers as L
from ..framework import LayerHelper, maybe_remat, name_scope
from ..layers import attention as A
from ..ops.fused_ce import chunked_softmax_cross_entropy
from .. import initializer as init


@dataclasses.dataclass
class TransformerConfig:
    src_vocab: int = 32000
    trg_vocab: int = 32000
    max_len: int = 256
    d_model: int = 512
    d_inner: int = 2048
    num_heads: int = 8
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    dropout: float = 0.1
    label_smooth_eps: float = 0.1
    use_flash: bool = False
    # one [d,3,d] (self) / [d,2,d] (cross K/V) projection matmul per
    # attention instead of three — see layers/attention.py fuse_qkv
    fuse_qkv: bool = False
    # chunked logits-free CE (ops/fused_ce.py); chunk = vocab tile width
    fused_ce: bool = False
    ce_chunk: int = 4096
    # per-block jax.checkpoint: drop intra-layer activations, recompute
    # in backward (memory_optimize analog). False still honors the
    # ambient framework.remat_mode the Trainer sets from strategy.remat.
    remat: bool = False
    # stacked-block representation (layers.stacked): per-layer params on
    # a leading [L, ...] axis — required for pipeline parallelism
    # (DistStrategy.pp_microbatches) and scan-compiled on a single chip
    # (one traced layer body instead of L unrolled copies: ~L x faster
    # compiles). Dropout works on the scan path (per-layer rng_fold);
    # the pipeline path still needs dropout == 0.
    stacked: bool = False
    dtype: str = "float32"


def base_config(**kw) -> TransformerConfig:
    return TransformerConfig(**kw)


def _embed(ids, vocab, d_model, dtype, scope_name):
    with name_scope(scope_name):
        emb = L.embedding(ids, size=[vocab, d_model], dtype=dtype,
                          param_attr=None)
    return emb * (d_model ** 0.5)


def encoder_layer(x, cfg: TransformerConfig, mask):
    h = L.layer_norm(x, begin_norm_axis=2)
    h = A.multi_head_attention(h, num_heads=cfg.num_heads, attn_mask=mask,
                               dropout_rate=cfg.dropout, use_flash=cfg.use_flash,
                               fuse_qkv=cfg.fuse_qkv)
    x = x + L.dropout(h, cfg.dropout, dropout_implementation="upscale_in_train")
    h = L.layer_norm(x, begin_norm_axis=2)
    h = A.ffn(h, cfg.d_inner, dropout_rate=cfg.dropout)
    return x + L.dropout(h, cfg.dropout, dropout_implementation="upscale_in_train")


def decoder_layer(x, enc_out, cfg: TransformerConfig, self_mask, cross_mask,
                  cache: Optional[dict] = None):
    h = L.layer_norm(x, begin_norm_axis=2)
    if cache is not None:
        h, cache = A.multi_head_attention(h, num_heads=cfg.num_heads, causal=False,
                                          dropout_rate=0.0, cache=cache,
                                          fuse_qkv=cfg.fuse_qkv)
    else:
        h = A.multi_head_attention(h, num_heads=cfg.num_heads, causal=True,
                                   attn_mask=self_mask, dropout_rate=cfg.dropout,
                                   use_flash=cfg.use_flash, fuse_qkv=cfg.fuse_qkv)
    x = x + L.dropout(h, cfg.dropout, dropout_implementation="upscale_in_train")
    h = L.layer_norm(x, begin_norm_axis=2)
    h = A.multi_head_attention(h, keys=enc_out, num_heads=cfg.num_heads,
                               attn_mask=cross_mask, dropout_rate=cfg.dropout,
                               fuse_qkv=cfg.fuse_qkv)
    x = x + L.dropout(h, cfg.dropout, dropout_implementation="upscale_in_train")
    h = L.layer_norm(x, begin_norm_axis=2)
    h = A.ffn(h, cfg.d_inner, dropout_rate=cfg.dropout)
    x = x + L.dropout(h, cfg.dropout, dropout_implementation="upscale_in_train")
    return (x, cache) if cache is not None else x


def encode(src_ids, cfg: TransformerConfig):
    dtype = jnp.dtype(cfg.dtype)
    x = _embed(src_ids, cfg.src_vocab, cfg.d_model, dtype, "src")
    x = x + A.positional_encoding(src_ids.shape[1], cfg.d_model, dtype)[None]
    x = L.dropout(x, cfg.dropout, dropout_implementation="upscale_in_train")
    mask = A.padding_mask(src_ids)
    with name_scope("encoder"):
        if cfg.stacked:
            from ..layers import stacked as S
            stack = S.encoder_stack_params(cfg.num_encoder_layers,
                                           cfg.d_model, cfg.d_inner)
            key_bias = mask[:, 0, 0, :]  # additive [b, s]
            x = S.apply_stacked(x, stack, S.make_encoder_block,
                                extras=key_bias, num_heads=cfg.num_heads,
                                use_flash=cfg.use_flash, remat=cfg.remat,
                                dropout_rate=cfg.dropout)
        else:
            for _ in range(cfg.num_encoder_layers):
                # fresh wrapper per layer: jax.checkpoint caches the traced
                # body per fn object, and each layer must trace (and create
                # its own params) separately
                x = maybe_remat(lambda a, m: encoder_layer(a, cfg, m),
                                enabled=cfg.remat or None)(x, mask)
        x = L.layer_norm(x, begin_norm_axis=2)
    return x, mask


def decode_hidden(trg_ids, enc_out, cross_mask, cfg: TransformerConfig):
    """Decoder stack up to (hidden states, vocab projection weight) —
    split out so the loss can run the projection chunked (fused_ce)."""
    dtype = jnp.dtype(cfg.dtype)
    x = _embed(trg_ids, cfg.trg_vocab, cfg.d_model, dtype, "trg")
    x = x + A.positional_encoding(trg_ids.shape[1], cfg.d_model, dtype)[None]
    x = L.dropout(x, cfg.dropout, dropout_implementation="upscale_in_train")
    with name_scope("decoder"):
        if cfg.stacked:
            from ..layers import stacked as S
            stack = S.decoder_stack_params(cfg.num_decoder_layers,
                                           cfg.d_model, cfg.d_inner)
            extras = {"enc": enc_out, "enc_bias": cross_mask[:, 0, 0, :]}
            x = S.apply_stacked(x, stack, S.make_decoder_block,
                                extras=extras, num_heads=cfg.num_heads,
                                use_flash=cfg.use_flash, causal=True,
                                remat=cfg.remat, dropout_rate=cfg.dropout)
        else:
            for _ in range(cfg.num_decoder_layers):
                x = maybe_remat(lambda a, e, cm: decoder_layer(a, e, cfg, None, cm),
                                enabled=cfg.remat or None)(x, enc_out, cross_mask)
        x = L.layer_norm(x, begin_norm_axis=2)
    helper = LayerHelper("logits_proj")
    w = helper.create_parameter("w", (cfg.d_model, cfg.trg_vocab), dtype,
                                initializer=init.Xavier())
    return x, w


def decode(trg_ids, enc_out, cross_mask, cfg: TransformerConfig):
    x, w = decode_hidden(trg_ids, enc_out, cross_mask, cfg)
    return jnp.matmul(x, w)


def make_decoder(cfg: TransformerConfig, max_len: int, beam_size: int = 1,
                 bos_id: int = 1, eos_id: int = 2, length_penalty_alpha: float = 0.0):
    """Incremental decoding program (beam_search_op capability): cached
    self-attention KV, one token per step, greedy or beam. Shares
    parameter names with make_model's train program, so params from a
    trained Trainer scope load directly.

    Returns a program fn: (src_ids [b, s]) -> ids [b, max_len] (greedy)
    or [b, beam, max_len] (beam)."""
    from ..core.errors import enforce
    from ..framework import reuse_names
    from ..layers.beam_search import beam_search, greedy_search

    enforce(not cfg.stacked,
            "make_decoder (incremental decoding) supports the per-layer "
            "param layout only; build it with cfg.stacked=False")

    def decode_program(src_ids):
        dtype = jnp.dtype(cfg.dtype)
        b = src_ids.shape[0]
        enc_out, src_mask = encode(src_ids, cfg)
        K = beam_size
        if K > 1:
            # tile encoder outputs per beam
            enc_out = jnp.repeat(enc_out, K, axis=0)
            src_mask = jnp.repeat(src_mask, K, axis=0)
        rows = b * K
        head_dim = cfg.d_model // cfg.num_heads
        caches = [
            {"k": jnp.zeros((rows, cfg.num_heads, max_len, head_dim), dtype),
             "v": jnp.zeros((rows, cfg.num_heads, max_len, head_dim), dtype),
             "index": jnp.asarray(0, jnp.int32)}
            for _ in range(cfg.num_decoder_layers)
        ]
        pe = A.positional_encoding(max_len, cfg.d_model, dtype)

        def run_step(tokens, caches):
            with reuse_names():
                pos = caches[0]["index"]
                with name_scope("trg"):
                    x = L.embedding(tokens, size=[cfg.trg_vocab, cfg.d_model],
                                    dtype=cfg.dtype) * (cfg.d_model ** 0.5)
                x = x[:, None, :]  # [rows, 1, d_model]
                x = x + jax.lax.dynamic_slice_in_dim(pe, pos, 1, axis=0)[None]
                new_caches = []
                with name_scope("decoder"):
                    for li in range(cfg.num_decoder_layers):
                        x, c = decoder_layer(x, enc_out, cfg, None, src_mask,
                                             cache=caches[li])
                        new_caches.append(c)
                    x = L.layer_norm(x, begin_norm_axis=2)
                helper = LayerHelper("logits_proj")
                w = helper.create_parameter("w", (cfg.d_model, cfg.trg_vocab), dtype,
                                            initializer=init.Xavier())
                logits = jnp.matmul(x[:, 0], w)
                return jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1), new_caches

        # materialize params once outside the scan (init-mode safety)
        _, caches0 = run_step(jnp.full((rows,), bos_id, jnp.int32), caches)
        del caches0
        if K > 1:
            seqs, scores = beam_search(run_step, caches, b, K, max_len,
                                       bos_id=bos_id, eos_id=eos_id,
                                       length_penalty_alpha=length_penalty_alpha)
            return {"ids": seqs, "scores": scores}
        seqs = greedy_search(run_step, caches, rows, max_len, bos_id=bos_id,
                             eos_id=eos_id)
        return {"ids": seqs}

    return decode_program


def make_model(cfg: TransformerConfig):
    """Program fn: (src_ids[b,s], trg_ids[b,t], labels[b,t]) -> dict.
    Loss = label-smoothed CE over non-pad target tokens, matching the
    reference benchmark's objective."""

    def transformer(src_ids, trg_ids, labels):
        enc_out, src_mask = encode(src_ids, cfg)
        eps = cfg.label_smooth_eps
        lab = labels.astype(jnp.int32)
        nonpad = (labels != 0).astype(jnp.float32)
        token_count = jnp.maximum(nonpad.sum(), 1.0)
        if cfg.fused_ce:
            # Chunked projection+CE: never materializes [b,t,vocab]
            # logits (ops/fused_ce.py) — the LM-head HBM hot spot.
            x, w = decode_hidden(trg_ids, enc_out, src_mask, cfg)
            b, t, d = x.shape
            ce = chunked_softmax_cross_entropy(
                x.reshape(b * t, d), w, None, lab.reshape(-1), eps,
                cfg.ce_chunk).reshape(b, t)
            loss = jnp.sum(ce * nonpad) / token_count
            return {"loss": loss, "token_count": token_count}
        logits = decode(trg_ids, enc_out, src_mask, cfg)
        # Label-smoothed CE without materializing a [b,t,vocab] one-hot:
        # loss = (1-eps)·NLL(target) + eps·mean(-logp) — algebraically
        # identical to smoothing over the uniform prior, HBM-friendly.
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, lab[..., None], axis=-1)[..., 0]
        ce = (1.0 - eps) * nll - eps * jnp.mean(logp, axis=-1)
        loss = jnp.sum(ce * nonpad) / token_count
        return {"loss": loss, "logits": logits, "token_count": token_count}

    return transformer
