"""Declarative alert engine over collector time series.

An alert rule is one line of a small expression grammar evaluated
against the :class:`~paddle_tpu.telemetry.collector.SeriesStore` a
collector maintains (per-origin bounded rings of every pushed metric
sample). Four forms cover the failure shapes the metric name table
actually produces::

    paddle_tpu_serving_breaker_open > 0 for 10s            # threshold
    rate(paddle_tpu_serving_rejected_total[30s]) > 1 for 30s   # rate
    p99(paddle_tpu_serving_latency_seconds[60s]) > 0.5 for 60s # quantile
    absent(paddle_tpu_serving_submitted_total[15s]) for 15s    # absence
    absent(origin[10s]) for 10s                 # origin push staleness

- **threshold** — the latest sample of every matching series compared
  against the bound (gauges, mostly: breaker open, queue depth).
- **rate** — per-second increase of a counter over the bracketed
  window (rejects/s, pushes-lost/s; the rate of a ``*_seconds_total``
  counter is a FRACTION of wall time, which is how the feeder
  starvation preset reads).
- **quantile** — ``p50``/``p90``/``p95``/``p99`` of a histogram's
  bucket counts DELTA over the window (the ``_bucket`` series done
  server-side; an idle window yields no verdict rather than a stale
  all-time quantile).
- **absence** — a tracked series (or, with the special target
  ``origin``, any origin's push stream) with no sample newer than the
  window. The replica-down pager: a SIGKILLed process stops pushing,
  its origin goes stale, the alert fires.

Every rule carries ``for N s``: the condition must hold continuously
that long before the alert transitions to **firing** (one flap does
not page), and a firing alert whose condition clears transitions to
**resolved** (kept listed for a while — ``/alerts`` shows both).
Matching is per SERIES (labels subset-match; the merged store's
``origin`` label included), so one rule yields one alert instance per
origin/replica/inst that trips it.

Rules are data (name + expr + severity), loadable from a JSON file,
and statically lintable against the known metric name table —
``tools/alert_check.py`` validates a rule file offline (unknown
metric, unknown label, malformed expr, form/metric-type mismatch ⇒
named findings, exit 0/1/3 like ``lint_gate.py``), and the CI ships
:data:`PRESET_PACK` through it.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

# -- the known metric name table ----------------------------------------------
# Every family any subsystem exports (the MIGRATION.md "Telemetry"
# table, kept in code so the alert linter has a machine-readable
# ground truth): name -> (type, label names the publisher stamps).
# ``origin`` (collector merge), ``replica`` (fleet merge), and ``inst``
# are legal on ANY series — see UNIVERSAL_LABELS.

# ``stale`` is stamped by the collector's merged export on origins
# past half their expiry (it never reaches the SeriesStore rings, but
# a rule matcher naming it must not lint as unknown)
UNIVERSAL_LABELS = frozenset({"origin", "replica", "inst", "stale"})

METRIC_TABLE: Dict[str, Tuple[str, frozenset]] = {
    # trainer / fit / resilience
    "paddle_tpu_trainer_steps_total": ("counter", frozenset()),
    "paddle_tpu_trainer_dispatches_total": ("counter", frozenset({"kind"})),
    "paddle_tpu_trainer_dispatch_seconds_total": ("counter", frozenset()),
    "paddle_tpu_trainer_global_step": ("gauge", frozenset()),
    "paddle_tpu_trainer_guard_incidents_total": ("counter", frozenset()),
    "paddle_tpu_trainer_checkpoints_total": ("counter", frozenset({"kind"})),
    "paddle_tpu_trainer_preemptions_total": ("counter", frozenset()),
    "paddle_tpu_trainer_resizes_total": ("counter", frozenset()),
    "paddle_tpu_resilience_reshards_total": ("counter", frozenset()),
    # input pipeline
    "paddle_tpu_feeder_stage_seconds_total": ("counter", frozenset({"stage"})),
    "paddle_tpu_feeder_batches_total": ("counter", frozenset()),
    "paddle_tpu_feeder_chunks_total": ("counter", frozenset()),
    "paddle_tpu_feeder_h2d_bytes_total": ("counter", frozenset()),
    "paddle_tpu_feeder_encode_saved_bytes_total": ("counter", frozenset()),
    "paddle_tpu_feeder_consumer_starved_seconds_total":
        ("counter", frozenset()),
    # serving
    "paddle_tpu_serving_submitted_total": ("counter", frozenset()),
    "paddle_tpu_serving_completed_total": ("counter", frozenset()),
    "paddle_tpu_serving_rejected_total": ("counter", frozenset({"reason"})),
    "paddle_tpu_serving_timeouts_total": ("counter", frozenset()),
    "paddle_tpu_serving_errors_total": ("counter", frozenset()),
    "paddle_tpu_serving_hangs_total": ("counter", frozenset()),
    "paddle_tpu_serving_workers_replaced_total": ("counter", frozenset()),
    "paddle_tpu_serving_reloads_total": ("counter", frozenset({"outcome"})),
    "paddle_tpu_serving_coalesced_batches_total": ("counter", frozenset()),
    "paddle_tpu_serving_coalesced_requests_total": ("counter", frozenset()),
    "paddle_tpu_serving_latency_seconds": ("histogram", frozenset()),
    "paddle_tpu_serving_queue_depth": ("gauge", frozenset()),
    "paddle_tpu_serving_queue_capacity": ("gauge", frozenset()),
    "paddle_tpu_serving_workers": ("gauge", frozenset()),
    "paddle_tpu_serving_workers_busy": ("gauge", frozenset()),
    "paddle_tpu_serving_breaker_open": ("gauge", frozenset()),
    "paddle_tpu_serving_breaker_half_open": ("gauge", frozenset()),
    "paddle_tpu_serving_breaker_trips_total": ("counter", frozenset()),
    "paddle_tpu_serving_generation": ("gauge", frozenset()),
    # async-PS
    "paddle_tpu_ps_trainer_step": ("gauge", frozenset()),
    "paddle_tpu_ps_pushes_total": ("counter", frozenset()),
    "paddle_tpu_ps_pulls_total": ("counter", frozenset()),
    "paddle_tpu_ps_reconnects_total": ("counter", frozenset()),
    "paddle_tpu_ps_retries_total": ("counter", frozenset()),
    "paddle_tpu_ps_pushes_lost_total": ("counter", frozenset()),
    # fleet router
    "paddle_tpu_fleet_submitted_total": ("counter", frozenset()),
    "paddle_tpu_fleet_routed_total": ("counter", frozenset({"replica"})),
    "paddle_tpu_fleet_rerouted_total": ("counter", frozenset()),
    "paddle_tpu_fleet_shed_total": ("counter", frozenset()),
    "paddle_tpu_fleet_replicas_replaced_total": ("counter", frozenset()),
    "paddle_tpu_fleet_replicas_grown_total": ("counter", frozenset()),
    "paddle_tpu_fleet_replicas_retired_total": ("counter", frozenset()),
    "paddle_tpu_fleet_reloads_total": ("counter", frozenset({"outcome"})),
    "paddle_tpu_fleet_reload_rollbacks_total": ("counter", frozenset()),
    "paddle_tpu_fleet_replicas_live": ("gauge", frozenset()),
    "paddle_tpu_fleet_replicas_ready": ("gauge", frozenset()),
    # autoscaler (the closed loop over this plane)
    "paddle_tpu_autoscaler_ticks_total": ("counter", frozenset()),
    "paddle_tpu_autoscaler_scale_ups_total": ("counter", frozenset()),
    "paddle_tpu_autoscaler_scale_downs_total": ("counter", frozenset()),
    "paddle_tpu_autoscaler_holds_total": ("counter", frozenset({"reason"})),
    "paddle_tpu_autoscaler_replicas": ("gauge", frozenset()),
    # telemetry shipping (this PR's own publishers)
    "paddle_tpu_shipper_shipped_total": ("counter", frozenset()),
    "paddle_tpu_shipper_dropped_total": ("counter", frozenset()),
    "paddle_tpu_shipper_snapshots_total": ("counter", frozenset()),
    "paddle_tpu_shipper_flushes_total": ("counter", frozenset({"outcome"})),
    "paddle_tpu_shipper_flush_seconds_total": ("counter", frozenset()),
    "paddle_tpu_collector_events_total": ("counter", frozenset()),
    "paddle_tpu_collector_snapshots_total": ("counter", frozenset()),
    "paddle_tpu_collector_origins": ("gauge", frozenset()),
    "paddle_tpu_collector_alerts_firing": ("gauge", frozenset()),
    "paddle_tpu_collector_alert_transitions_total":
        ("counter", frozenset({"state"})),
    # the durable series store (collector-side persistence)
    "paddle_tpu_collector_segments_corrupt_total": ("counter", frozenset()),
    "paddle_tpu_collector_store_appends_total": ("counter", frozenset()),
    "paddle_tpu_collector_store_bytes_total": ("counter", frozenset()),
    "paddle_tpu_collector_store_append_seconds_total":
        ("counter", frozenset()),
    "paddle_tpu_collector_store_append_failures_total":
        ("counter", frozenset()),
    "paddle_tpu_collector_store_segments": ("gauge", frozenset()),
    "paddle_tpu_telemetry_scrape_aborted_total": ("counter", frozenset()),
}

# the special absence target: any tracked origin's push stream
ORIGIN_TARGET = "origin"

_CMP_FNS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b, "!=": lambda a, b: a != b,
}

_SERIES_RE = re.compile(
    r"^(?P<name>[a-z_][a-z0-9_]*)(\{(?P<labels>[^}]*)\})?$")
_DUR_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m|h)$")
_QUANT_FNS = {"p50": 0.50, "p90": 0.90, "p95": 0.95, "p99": 0.99}
_DUR_UNITS = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}


class AlertRuleError(ValueError):
    """A rule failed to parse (the linter reports this as a
    ``alert:malformed-expr`` finding instead of raising)."""


def parse_duration(text: str) -> float:
    m = _DUR_RE.match(text.strip())
    if not m:
        raise AlertRuleError(f"bad duration {text!r} (want e.g. 30s, 5m)")
    return float(m.group(1)) * _DUR_UNITS[m.group(2)]


def _parse_labels(text: Optional[str]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in (text or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise AlertRuleError(f"bad label matcher {part!r} (want k=v)")
        k, _, v = part.partition("=")
        out[k.strip()] = v.strip().strip('"')
    return out


def _parse_series(text: str) -> Tuple[str, Dict[str, str]]:
    m = _SERIES_RE.match(text.strip())
    if not m:
        raise AlertRuleError(
            f"bad series {text!r} (want metric_name{{label=value,...}})")
    return m.group("name"), _parse_labels(m.group("labels"))


def _split_windowed(text: str) -> Tuple[str, Optional[float]]:
    """``series[30s]`` → (``series``, 30.0); plain series → (.., None)."""
    if text.endswith("]") and "[" in text:
        series, _, win = text[:-1].rpartition("[")
        return series, parse_duration(win)
    return text, None


@dataclass
class AlertRule:
    """One parsed rule. ``form`` is threshold|rate|quantile|absence;
    ``metric`` is None only for the ``absent(origin[..])`` form."""

    name: str
    expr: str
    form: str
    metric: Optional[str]
    labels: Dict[str, str] = field(default_factory=dict)
    op: str = ">"
    threshold: float = 0.0
    window_s: Optional[float] = None
    q: Optional[float] = None
    for_s: float = 0.0
    severity: str = "warn"
    annotations: Dict[str, Any] = field(default_factory=dict)

    def describe(self) -> Dict[str, Any]:
        return {"name": self.name, "expr": self.expr, "form": self.form,
                "metric": self.metric, "for_s": self.for_s,
                "severity": self.severity}


def parse_rule(name: str, expr: str, severity: str = "warn",
               annotations: Optional[Dict[str, Any]] = None) -> AlertRule:
    """Parse one rule expression (grammar in the module docstring)."""
    text = " ".join(expr.split())
    for_s = 0.0
    if " for " in text:
        text, _, dur = text.rpartition(" for ")
        for_s = parse_duration(dur)
    kw: Dict[str, Any] = dict(name=name, expr=expr, severity=severity,
                              annotations=dict(annotations or {}),
                              for_s=for_s)

    if text.startswith("absent(") and text.endswith(")"):
        inner, window = _split_windowed(text[len("absent("):-1].strip())
        if window is None:
            raise AlertRuleError(
                f"{name}: absent() needs a staleness window, e.g. "
                "absent(metric[15s])")
        if inner == ORIGIN_TARGET:
            return AlertRule(form="absence", metric=None,
                             window_s=window, **kw)
        metric, labels = _parse_series(inner)
        return AlertRule(form="absence", metric=metric, labels=labels,
                         window_s=window, **kw)

    # the comparison tail: <atom> <op> <number>
    m = re.match(r"^(?P<atom>.+?)\s*(?P<op>>=|<=|==|!=|>|<)\s*"
                 r"(?P<num>-?\d+(?:\.\d+)?(?:e-?\d+)?)$", text)
    if not m:
        raise AlertRuleError(
            f"{name}: expected '<expr> <op> <number> [for <dur>]', "
            f"got {expr!r}")
    atom, op, num = m.group("atom").strip(), m.group("op"), float(m.group("num"))
    kw.update(op=op, threshold=num)

    fn_m = re.match(r"^(?P<fn>rate|p50|p90|p95|p99)\((?P<arg>.+)\)$", atom)
    if fn_m:
        fn, arg = fn_m.group("fn"), fn_m.group("arg").strip()
        inner, window = _split_windowed(arg)
        if window is None:
            raise AlertRuleError(
                f"{name}: {fn}() needs a window, e.g. {fn}(metric[30s])")
        metric, labels = _parse_series(inner)
        if fn == "rate":
            return AlertRule(form="rate", metric=metric, labels=labels,
                             window_s=window, **kw)
        return AlertRule(form="quantile", metric=metric, labels=labels,
                         window_s=window, q=_QUANT_FNS[fn], **kw)

    metric, labels = _parse_series(atom)
    return AlertRule(form="threshold", metric=metric, labels=labels, **kw)


def parse_rules(specs: List[Dict[str, Any]]) -> List[AlertRule]:
    """Parse the JSON-able rule-pack shape: a list of ``{"name": ...,
    "expr": ..., "severity"?: ..., "annotations"?: {...}}``."""
    out = []
    for spec in specs:
        out.append(parse_rule(spec["name"], spec["expr"],
                              severity=spec.get("severity", "warn"),
                              annotations=spec.get("annotations")))
    return out


def load_rules(path: str) -> List[AlertRule]:
    """Load + parse a JSON rule file (the ``--rules`` input of the
    collector daemon and ``tools/alert_check.py``)."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        doc = doc.get("rules", [])
    return parse_rules(doc)


# -- static lint (tools/alert_check.py) ---------------------------------------


def lint_rules(specs: List[Dict[str, Any]],
               table: Optional[Dict[str, Tuple[str, frozenset]]] = None
               ) -> List[str]:
    """Validate a rule pack (the raw JSON-able list) against the known
    metric name table. Returns named findings (empty == clean):

    - ``alert:malformed-expr`` — the expression does not parse;
    - ``alert:unknown-metric`` — the metric is not in the table;
    - ``alert:unknown-label`` — a label matcher the publisher never
      stamps (and is not a universal origin/replica/inst label);
    - ``alert:type-mismatch`` — ``rate()`` of a non-counter,
      ``p99()`` of a non-histogram, or a bare threshold on a
      histogram;
    - ``alert:bad-duration`` — ``for_s`` shorter than the window makes
      a rate/quantile rule flappy (info-grade, still listed);
    - ``alert:duplicate-name`` — two rules sharing a name would share
      one alert state.
    """
    table = METRIC_TABLE if table is None else table
    findings: List[str] = []
    seen: Dict[str, int] = {}
    for i, spec in enumerate(specs):
        if not isinstance(spec, dict):
            # user-malformed input is a FINDING (exit 1), never a
            # linter crash (exit 3)
            findings.append(
                f"alert:malformed-expr rule[{i}]: expected an object "
                f"{{name, expr, ...}}, got {type(spec).__name__}")
            continue
        rname = str(spec.get("name") or f"rule[{i}]")
        if not spec.get("name"):
            findings.append(f"alert:malformed-expr {rname}: missing 'name'")
        if rname in seen:
            findings.append(
                f"alert:duplicate-name {rname}: also rule #{seen[rname]} — "
                "two rules sharing a name share one alert state")
        seen[rname] = i
        expr = spec.get("expr")
        if not expr:
            findings.append(f"alert:malformed-expr {rname}: missing 'expr'")
            continue
        try:
            rule = parse_rule(rname, expr,
                              severity=spec.get("severity", "warn"))
        except AlertRuleError as e:
            findings.append(f"alert:malformed-expr {rname}: {e}")
            continue
        if spec.get("severity") not in (None, "info", "warn", "page"):
            findings.append(
                f"alert:malformed-expr {rname}: severity "
                f"{spec['severity']!r} not in info|warn|page")
        if rule.metric is None:  # absent(origin[..]) — nothing to check
            continue
        entry = table.get(rule.metric)
        if entry is None:
            findings.append(
                f"alert:unknown-metric {rname}: {rule.metric!r} is not in "
                "the metric name table (typo, or a family this build does "
                "not export)")
            continue
        mtype, mlabels = entry
        for ln in rule.labels:
            if ln not in mlabels and ln not in UNIVERSAL_LABELS:
                findings.append(
                    f"alert:unknown-label {rname}: {rule.metric} has no "
                    f"label {ln!r} (publisher stamps "
                    f"{sorted(mlabels) or 'none'}; "
                    f"{sorted(UNIVERSAL_LABELS)} are always legal)")
        if rule.form == "rate" and mtype != "counter":
            findings.append(
                f"alert:type-mismatch {rname}: rate() of {rule.metric} "
                f"({mtype}) — rate is only meaningful on counters")
        if rule.form == "quantile" and mtype != "histogram":
            findings.append(
                f"alert:type-mismatch {rname}: p{int((rule.q or 0) * 100)}()"
                f" of {rule.metric} ({mtype}) — quantiles need a histogram")
        if rule.form == "threshold" and mtype == "histogram":
            findings.append(
                f"alert:type-mismatch {rname}: bare threshold on histogram "
                f"{rule.metric} — compare a quantile (p99(...)) instead")
        if rule.form in ("rate", "quantile") and rule.window_s and \
                0 < rule.for_s < rule.window_s / 2:
            findings.append(
                f"alert:bad-duration {rname}: for {rule.for_s:g}s is much "
                f"shorter than the {rule.window_s:g}s window — the rule "
                "will flap on one noisy sample")
    return findings


# -- the preset pack ----------------------------------------------------------
# Derived from the MIGRATION.md metric name table: the conditions two
# bench rounds and five drills said should page, as data. Ships
# through tools/alert_check.py in CI (tier-1).

PRESET_PACK: List[Dict[str, Any]] = [
    {"name": "feeder_starvation", "severity": "warn",
     "expr": "rate(paddle_tpu_feeder_consumer_starved_seconds_total[30s])"
             " > 0.5 for 30s",
     "annotations": {"summary": "training loop starved of input >50% of "
                                "wall time (the BENCH_r05 degraded-link "
                                "signature)"}},
    {"name": "serving_shed_rate", "severity": "warn",
     "expr": "rate(paddle_tpu_serving_rejected_total[30s]) > 1 for 30s",
     "annotations": {"summary": "serving front door shedding >1 req/s"}},
    {"name": "fleet_shed_rate", "severity": "warn",
     "expr": "rate(paddle_tpu_fleet_shed_total[30s]) > 1 for 30s",
     "annotations": {"summary": "fleet router shedding >1 req/s (every "
                                "replica rejecting)"}},
    {"name": "serving_p99_latency", "severity": "warn",
     "expr": "p99(paddle_tpu_serving_latency_seconds[60s]) > 0.5 for 60s",
     "annotations": {"summary": "served p99 latency above 500ms"}},
    {"name": "serving_breaker_open", "severity": "page",
     "expr": "paddle_tpu_serving_breaker_open > 0 for 10s",
     "annotations": {"summary": "a replica's circuit breaker is open"}},
    {"name": "ps_pushes_lost", "severity": "warn",
     "expr": "rate(paddle_tpu_ps_pushes_lost_total[60s]) > 0.1 for 60s",
     "annotations": {"summary": "async-PS dropping gradient pushes "
                                "(at-most-once replies lost)"}},
    {"name": "guard_incidents", "severity": "warn",
     "expr": "rate(paddle_tpu_trainer_guard_incidents_total[60s]) > 0.1 "
             "for 60s",
     "annotations": {"summary": "NaN/Inf guard discarding steps"}},
    {"name": "journal_drops", "severity": "warn",
     "expr": "rate(paddle_tpu_shipper_dropped_total[60s]) > 1 for 60s",
     "annotations": {"summary": "telemetry shipper dropping journal "
                                "events (collector unreachable or "
                                "buffer-bound too low)"}},
    {"name": "origin_down", "severity": "page",
     "expr": "absent(origin[10s]) for 10s",
     "annotations": {"summary": "a process that was shipping telemetry "
                                "went silent (replica/trainer down?)"}},
]


def preset_rules(for_s: Optional[float] = None,
                 window_s: Optional[float] = None) -> List[AlertRule]:
    """The parsed preset pack. ``for_s``/``window_s`` override every
    rule's durations — the drill/test knob that keeps the SAME preset
    conditions but on a seconds-not-minutes clock."""
    rules = parse_rules(PRESET_PACK)
    for r in rules:
        if for_s is not None:
            r.for_s = float(for_s)
        if window_s is not None and r.window_s is not None:
            r.window_s = float(window_s)
    return rules


# -- the engine ---------------------------------------------------------------


def _json_value(v):
    """Alert values cross JSON surfaces (``/alerts`` bodies, journaled
    transitions, flight-dump detail): a non-finite float (an overflow-
    bucket quantile is legitimately +inf) must not serialize as the
    invalid-JSON ``Infinity`` token — it becomes the string ``"inf"``
    instead. Comparisons happen BEFORE this, on the real float."""
    import math

    if isinstance(v, float) and not math.isfinite(v):
        return repr(v)
    return v


class AlertEngine:
    """Firing→resolved state machine over a rule list.

    :meth:`evaluate` reads the store once per tick and advances every
    rule's per-series state: condition true → *pending* (since t);
    held ``for_s`` → **firing** (one transition); condition false
    while firing → **resolved** (one transition). A series/origin that
    vanishes from the store (origin expiry after a ``replace()``)
    clears its condition — which is how a replica-down absence alert
    resolves once the dead origin is retired. Transitions are returned
    AND handed to ``on_transition(dict)`` (the collector journals them
    and can trigger a flight dump); state reads are
    :meth:`snapshot`."""

    def __init__(self, rules: List[AlertRule],
                 on_transition: Optional[Callable[[Dict[str, Any]],
                                                  None]] = None,
                 resolved_keep_s: float = 600.0):
        import threading

        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise AlertRuleError(f"duplicate rule names in {sorted(names)}")
        # swapped as a whole list under _lock (load_rules); readers
        # iterate whichever complete snapshot reference they grabbed —
        # per-instance alert STATE is what _lock actually guards
        self.rules = list(rules)   # lint: allow(thread:unguarded-access)
        self.on_transition = on_transition
        self.resolved_keep_s = float(resolved_keep_s)
        # guards _active/_resolved/transitions_total: the eval thread
        # mutates them while /alerts scrapes and drill polls snapshot()
        # from other threads
        self._lock = threading.Lock()
        # (rule name, series key) -> {"state": pending|firing, "since",
        # "value"}; resolved instances move to _resolved
        self._active: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._resolved: List[Dict[str, Any]] = []
        self.transitions_total: Dict[str, int] = {"firing": 0, "resolved": 0}

    # -- condition evaluation ------------------------------------------------

    def _conditions(self, rule: AlertRule, store,
                    now: float) -> Dict[str, float]:
        """``{series key: measured value}`` for every series where the
        rule's condition holds RIGHT NOW."""
        cmp_fn = _CMP_FNS[rule.op]
        out: Dict[str, float] = {}
        if rule.form == "absence":
            if rule.metric is None:
                pairs = store.origin_staleness(now)
            else:
                pairs = store.staleness(rule.metric, rule.labels, now)
            for key, age in pairs:
                if age > (rule.window_s or 0.0):
                    out[key] = age
            return out
        if rule.form == "threshold":
            pairs = store.latest_values(rule.metric, rule.labels, now)
        elif rule.form == "rate":
            pairs = store.rates(rule.metric, rule.labels, rule.window_s, now)
        else:  # quantile
            pairs = store.quantiles(rule.metric, rule.labels, rule.q,
                                    rule.window_s, now)
        for key, value in pairs:
            if value is not None and cmp_fn(value, rule.threshold):
                out[key] = value
        return out

    # -- the tick ------------------------------------------------------------

    def evaluate(self, store, now: Optional[float] = None
                 ) -> List[Dict[str, Any]]:
        import time as _time

        now = _time.time() if now is None else now
        transitions: List[Dict[str, Any]] = []
        # condition evaluation reads the store (its own lock) OUTSIDE
        # the engine lock; state mutation happens under it; callbacks
        # (journal emits, flight dumps — potentially slow) run AFTER
        # release so a dump never blocks an /alerts scrape
        conditions = [(rule, self._conditions(rule, store, now))
                      for rule in self.rules]
        with self._lock:
            for rule, true_now in conditions:
                # advance/enter
                for key, value in true_now.items():
                    st = self._active.get((rule.name, key))
                    if st is None:
                        st = {"state": "pending", "since": now,
                              "value": value}
                        self._active[(rule.name, key)] = st
                    st["value"] = value
                    if st["state"] == "pending" and \
                            now - st["since"] >= rule.for_s:
                        st["state"] = "firing"
                        st["fired_at"] = now
                        transitions.append(self._transition(rule, key, st,
                                                            "firing", now))
                # clear
                for (rname, key) in [k for k in self._active
                                     if k[0] == rule.name]:
                    if key in true_now:
                        continue
                    st = self._active.pop((rname, key))
                    if st["state"] == "firing":
                        st["resolved_at"] = now
                        st["rule"] = rule.name
                        st["key"] = key
                        st["severity"] = rule.severity
                        st["expr"] = rule.expr
                        self._resolved.append(st)
                        transitions.append(self._transition(rule, key, st,
                                                            "resolved",
                                                            now))
                    # a pending instance that cleared never fired: dropped
            self._resolved = [
                r for r in self._resolved
                if now - r["resolved_at"] <= self.resolved_keep_s]
            for t in transitions:
                self.transitions_total[t["state"]] += 1
        for t in transitions:
            if self.on_transition is not None:
                try:
                    self.on_transition(t)
                except Exception:  # alerting must not kill the eval loop
                    pass
        return transitions

    def _transition(self, rule: AlertRule, key: str, st: Dict[str, Any],
                    state: str, now: float) -> Dict[str, Any]:
        return {"rule": rule.name, "key": key, "state": state, "t": now,
                "value": _json_value(st.get("value")),
                "severity": rule.severity,
                "expr": rule.expr, "for_s": rule.for_s,
                "since": st.get("since"),
                "annotations": dict(rule.annotations)}

    # -- durable state (the collector's on-disk store) -----------------------

    def state(self) -> Dict[str, Any]:
        """JSON-able dump of the firing/pending/resolved state — what
        the collector's segment log persists so a restart (or a standby
        promotion) resumes every ``for_s`` clock and firing instance
        instead of re-arming from scratch."""
        with self._lock:
            return {
                "active": [[rname, key, dict(st, value=_json_value(
                    st.get("value")))]
                           for (rname, key), st in sorted(
                               self._active.items())],
                "resolved": [dict(r, value=_json_value(r.get("value")))
                             for r in self._resolved],
                "transitions_total": dict(self.transitions_total),
            }

    def restore(self, state: Dict[str, Any]) -> None:
        """Silently adopt a :meth:`state` dump: firing instances come
        back FIRING (their original ``since``/``fired_at`` clocks
        intact, NO ``firing`` transition emitted — the pager already
        went off before the restart), pending ones keep their held
        time, the resolved list and transition counters carry over.
        Instances of rules this engine no longer has are dropped."""
        known = {r.name for r in self.rules}
        with self._lock:
            self._active = {
                (rname, key): dict(st)
                for rname, key, st in (state.get("active") or [])
                if rname in known}
            self._resolved = [dict(r) for r in state.get("resolved") or []
                              if r.get("rule") in known]
            for k, v in (state.get("transitions_total") or {}).items():
                self.transitions_total[k] = int(v)

    def set_rules(self, rules: List[AlertRule]) -> List[Dict[str, Any]]:
        """Hot-swap the rule list (SIGHUP / ``POST /rules``). State is
        keyed by rule NAME, so a rule that persists across the reload
        keeps its firing/pending instances (an edited threshold takes
        effect at the next evaluation); instances of rules that
        vanished are closed — firing ones emit a ``resolved``
        transition (returned AND handed to ``on_transition``), pending
        ones are dropped silently."""
        import time as _time

        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise AlertRuleError(f"duplicate rule names in {sorted(names)}")
        now = _time.time()
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            old_by_name = {r.name: r for r in self.rules}
            keep = set(names)
            for (rname, key) in [k for k in self._active
                                 if k[0] not in keep]:
                st = self._active.pop((rname, key))
                if st["state"] == "firing":
                    rule = old_by_name[rname]
                    st["resolved_at"] = now
                    st.update(rule=rname, key=key, severity=rule.severity,
                              expr=rule.expr)
                    self._resolved.append(st)
                    transitions.append(self._transition(rule, key, st,
                                                        "resolved", now))
            for t in transitions:
                self.transitions_total[t["state"]] += 1
            self.rules = list(rules)
        for t in transitions:
            if self.on_transition is not None:
                try:
                    self.on_transition(t)
                except Exception:
                    pass
        return transitions

    # -- reads ---------------------------------------------------------------

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``/alerts`` payload: firing + pending instances and the
        recently-resolved list."""
        import time as _time

        now = _time.time() if now is None else now
        firing, pending = [], []
        with self._lock:
            # rules are copied under the SAME lock as the instance
            # table: set_rules()/restore() mutate both at runtime now,
            # and a scrape racing a hot-reload/promotion must see one
            # consistent pair (plus .get below: recovery assigns
            # .rules outside the engine lock by design)
            by_name = {r.name: r for r in self.rules}
            active = {k: dict(v) for k, v in self._active.items()}
            resolved_src = [dict(r) for r in self._resolved]
            trans = dict(self.transitions_total)
        for (rname, key), st in sorted(active.items()):
            rule = by_name.get(rname)
            if rule is None:
                continue  # instance of a rule mid-swap: next tick's view
            entry = {"rule": rname, "key": key, "state": st["state"],
                     "since": st["since"], "held_s": round(now - st["since"],
                                                           3),
                     "value": _json_value(st.get("value")),
                     "severity": rule.severity,
                     "expr": rule.expr, "for_s": rule.for_s,
                     "annotations": dict(rule.annotations)}
            (firing if st["state"] == "firing" else pending).append(entry)
        resolved = [{"rule": r["rule"], "key": r["key"],
                     "resolved_at": r["resolved_at"],
                     "fired_at": r.get("fired_at"),
                     "value": _json_value(r.get("value")),
                     "severity": r["severity"],
                     "expr": r["expr"]}
                    for r in resolved_src]
        return {"firing": firing, "pending": pending, "resolved": resolved,
                "rules": [r.describe() for r in self.rules],
                "transitions_total": trans}

    def firing(self) -> List[Dict[str, Any]]:
        return self.snapshot()["firing"]


__all__ = [
    "METRIC_TABLE", "ORIGIN_TARGET", "PRESET_PACK", "UNIVERSAL_LABELS",
    "AlertEngine", "AlertRule", "AlertRuleError", "lint_rules", "load_rules",
    "parse_duration", "parse_rule", "parse_rules", "preset_rules",
]
