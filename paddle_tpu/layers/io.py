"""Graph-embedded IO surface (python/paddle/fluid/layers/io.py).

The reference embeds the data pipeline in the program (py_reader blocking
queues, recordio reader ops, shuffle/batch/double-buffer decorator ops —
operators/reader/*). TPU-native equivalent: the pipeline is host-side
(data/reader.py combinators + data/feeder.py device prefetch), and these
functions keep the fluid API names, delegating to it. ``data()`` returns
a ShapeDtypeStruct placeholder for program tracing.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtypes import convert_dtype
from ..data import reader as _reader
from ..data.feeder import DataFeeder, DeviceFeeder
from .. import recordio as _recordio


def data(name: str, shape: Sequence[int], dtype="float32", lod_level: int = 0,
         append_batch_size: bool = True):
    """fluid.layers.data analog: a typed placeholder (ShapeDtypeStruct)
    used as an example arg when tracing/compiling a Program. A leading
    batch dim of 1 stands in for the runtime batch (append_batch_size)."""
    full = ([1] if append_batch_size else []) + [abs(s) if s != -1 else 1 for s in shape]
    return jax.ShapeDtypeStruct(tuple(full), convert_dtype(dtype))


def batch(reader, batch_size: int, drop_last: bool = False):
    """layers.io.batch = reader-level batching (batch_op analog)."""
    return _reader.batch(reader, batch_size, drop_last=drop_last)


def shuffle(reader, buffer_size: int):
    """layers.io.shuffle (shuffle_reader op analog)."""
    return _reader.shuffle(reader, buffer_size)


def double_buffer(reader, place=None, name=None):
    """double_buffer_reader analog: host→device prefetch of one batch
    ahead. Returns a generator of device arrays."""
    return DeviceFeeder(reader)


def py_reader(capacity: int, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer: bool = True):
    """create_py_reader analog (lod_tensor_blocking_queue.h): a
    background-thread feeding queue. Returns a PyReader with
    decorate_paddle_reader/start/reset, yielding ready device batches."""
    return PyReader(capacity, use_double_buffer=use_double_buffer)


class PyReader:
    """Python-fed async reader (reader/create_py_reader_op.cc capability):
    a bounded queue filled by a background thread, drained by the train
    loop — the host-side overlap the reference got from the blocking
    queue + double_buffer ops."""

    def __init__(self, capacity: int, use_double_buffer: bool = True):
        self.capacity = capacity
        self.use_double_buffer = use_double_buffer
        self._reader = None

    def decorate_paddle_reader(self, reader):
        self._reader = reader

    decorate_sample_list_generator = decorate_paddle_reader
    decorate_batch_generator = decorate_paddle_reader

    def start(self):
        r = _reader.buffered(self._reader, self.capacity)
        if self.use_double_buffer:
            return DeviceFeeder(r)
        return r()

    def __call__(self):
        return self.start()

    def reset(self):
        pass


def open_files(filenames: Sequence[str], shapes=None, dtypes=None, lod_levels=None,
               thread_num: int = 1, buffer_size: Optional[int] = None):
    """open_files/open_recordio_file analog: a reader over recordio
    shards, round-robin by file, decoded to numpy tuples."""
    def _r():
        for fn in filenames:
            for rec in _recordio.reader_creator(fn)():
                yield rec
    if buffer_size:
        return _reader.buffered(_r, buffer_size)
    return _r


def read_file(reader):
    """read_file op analog: pull one batch from a started reader."""
    it = reader() if callable(reader) else iter(reader)
    return next(it)


def random_data_generator(low: float, high: float, shapes, lod_levels=None, name=None):
    """create_random_data_generator_op analog — the synthetic in-graph
    data source the reference uses widely in tests/benchmarks."""
    rng = np.random.RandomState(0)

    def _r():
        while True:
            yield tuple(rng.uniform(low, high, s).astype(np.float32) for s in shapes)
    return _r


class Preprocessor:
    """reader/create_custom_reader_op analog: attach a per-sample
    transform to a reader: ``Preprocessor(reader)(fn)``."""

    def __init__(self, reader, name=None):
        self.reader = reader

    def __call__(self, fn):
        return _reader.map_readers(fn, self.reader)


def load(out, file_path, load_as_fp16=None):
    """load_op analog (reference layers/io.py:1070, operators/load_op.cc):
    read one saved array from ``file_path`` (``.npy`` via numpy, or a
    single-entry ``.npz``). The reference mutates ``out`` in place; here
    the loaded array is returned (pass ``out=None`` or an exemplar whose
    dtype the result is checked against)."""
    import numpy as np

    import jax.numpy as jnp

    from ..core.errors import enforce

    arr = np.load(file_path, allow_pickle=False)
    if hasattr(arr, "files"):  # npz archive: exactly one entry
        enforce(len(arr.files) == 1,
                f"load: {file_path!r} holds {len(arr.files)} arrays; expected 1")
        arr = arr[arr.files[0]]
    if load_as_fp16:
        arr = arr.astype(np.float16)
    if out is not None and hasattr(out, "dtype") and not load_as_fp16:
        arr = arr.astype(out.dtype)
    return jnp.asarray(arr)
