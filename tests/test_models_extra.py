"""Smoke/convergence tests for seq2seq, AlexNet, GoogLeNet, SE-ResNeXt."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import optimizer as opt
from paddle_tpu.models import convnets, seq2seq


@pytest.mark.slow
def test_seq2seq_learns_copy():
    model = pt.build(seq2seq.make_model(src_vocab=15, trg_vocab=15, emb_dim=16,
                                        hidden=32))
    rng = np.random.RandomState(0)
    bs, s = 16, 5
    src = rng.randint(3, 15, (bs, s)).astype(np.int64)
    trg = np.zeros_like(src)
    trg[:, 0] = 1
    trg[:, 1:] = src[:, :-1]
    labels = np.concatenate([trg[:, 1:], np.full((bs, 1), 2)], axis=1).astype(np.int64)
    feed = {"src_ids": src, "trg_ids": trg, "labels": labels,
            "src_lengths": np.full((bs,), s, np.int64)}
    trainer = pt.Trainer(model, opt.Adam(5e-3), loss_name="loss")
    trainer.startup(sample_feed=feed)
    losses = [float(trainer.step(feed)["loss"]) for _ in range(80)]
    assert losses[-1] < losses[0] * 0.5, f"{losses[0]} -> {losses[-1]}"


def _img_feed(bs=2, size=64, classes=10):
    rng = np.random.RandomState(0)
    return {"image": rng.randn(bs, 3, size, size).astype(np.float32),
            "label": rng.randint(0, classes, (bs, 1)).astype(np.int64)}


@pytest.mark.slow
def test_alexnet_step():
    model = pt.build(convnets.make_alexnet(class_num=10))
    feed = _img_feed(size=224)
    trainer = pt.Trainer(model, opt.Momentum(0.01, 0.9), loss_name="loss")
    trainer.startup(sample_feed=feed)
    out = trainer.step(feed)
    assert np.isfinite(float(out["loss"]))


@pytest.mark.slow
def test_googlenet_step():
    model = pt.build(convnets.make_googlenet(class_num=10))
    feed = _img_feed(size=96)
    trainer = pt.Trainer(model, opt.Momentum(0.01, 0.9), loss_name="loss")
    trainer.startup(sample_feed=feed)
    out = trainer.step(feed)
    assert np.isfinite(float(out["loss"]))


@pytest.mark.slow
def test_se_resnext_step():
    model = pt.build(convnets.make_se_resnext(depth=50, class_num=10))
    feed = _img_feed(size=64)
    trainer = pt.Trainer(model, opt.Momentum(0.01, 0.9), loss_name="loss")
    trainer.startup(sample_feed=feed)
    out = trainer.step(feed)
    assert np.isfinite(float(out["loss"]))
