"""Semantic role labeling — the book `label_semantic_roles` config
(python/paddle/fluid/tests/book/test_label_semantic_roles.py: word +
predicate-mark embeddings → stacked alternating-direction LSTMs → per-
position scores → linear_chain_crf loss, crf_decoding inference).

TPU-native: padded [b, t] batches with explicit lengths (the LoD
equivalent, DESIGN.md "LoD decision"), scan-based LSTMs, the CRF from
layers.crf (forward algorithm under scan, Viterbi decode)."""

from __future__ import annotations

import jax.numpy as jnp

from .. import layers as L
from ..layers.crf import crf_decoding, linear_chain_crf
from ..layers.rnn import dynamic_lstm


def make_model(vocab_size=5000, num_labels=20, word_dim=32, hidden_dim=128,
               depth=4):
    """word_ids [b,t], mark_ids [b,t] (1 on the predicate span), label
    [b,t], lengths [b]. Stacked BiLSTM via alternating direction per
    layer, as the reference's 8-layer config does."""

    def srl_net(word_ids, mark_ids, label, lengths):
        word = L.embedding(word_ids, size=[vocab_size, word_dim], name="word_emb")
        mark = L.embedding(mark_ids, size=[2, word_dim], name="mark_emb")
        x = jnp.concatenate([word, mark], axis=-1)

        h, _ = dynamic_lstm(x, hidden_dim, sequence_length=lengths, name="lstm_0")
        for i in range(1, depth):
            rev = bool(i % 2)
            nxt, _ = dynamic_lstm(h, hidden_dim, sequence_length=lengths,
                                  is_reverse=rev, name=f"lstm_{i}")
            h = nxt + h  # residual keeps deep stacks trainable
        emission = L.fc(h, num_labels, num_flatten_dims=2, name="emission")

        nll, transition = linear_chain_crf(emission, label, lengths, name="crf")
        decoded = crf_decoding(emission, lengths, transition)
        mask = (jnp.arange(label.shape[1])[None, :] < lengths[:, None])
        correct = jnp.sum((decoded == label) & mask)
        acc = correct / jnp.maximum(jnp.sum(mask), 1)
        return {"loss": jnp.mean(nll), "decoded": decoded, "acc": acc}

    return srl_net
