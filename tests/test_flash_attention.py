"""Flash attention kernel vs XLA reference (interpret mode on CPU)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import flash_attention as fa


def _ref(q, k, v, causal=False, key_bias=None):
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if key_bias is not None:
        s = s + key_bias[:, None, None, :]
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        s = jnp.where(cm, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _rand(b=1, h=2, s=128, d=32, sk=None, seed=0):
    rng = np.random.RandomState(seed)
    sk = sk or s
    q = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, h, sk, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, h, sk, d).astype(np.float32))
    return q, k, v


def test_forward_matches_reference():
    q, k, v = _rand(s=128)
    out = fa.flash_attention(q, k, v, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)


def test_forward_causal():
    q, k, v = _rand(s=128)
    out = fa.flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v, causal=True)),
                               atol=2e-5, rtol=2e-5)


def test_forward_with_key_bias_padding():
    q, k, v = _rand(s=128)
    bias = jnp.where(jnp.arange(128)[None, :] < 100, 0.0, -1e9)  # [1, sk]
    out = fa.flash_attention(q, k, v, key_bias=bias, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_ref(q, k, v, key_bias=bias)),
                               atol=2e-5, rtol=2e-5)


def test_forward_uneven_blocks():
    # seq not a multiple of block: exercised via block > seq fallback
    q, k, v = _rand(s=96)
    out = fa.flash_attention(q, k, v, block_q=96, block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)


def test_cross_attention_different_kv_len():
    q, k, v = _rand(s=64, sk=128)
    out = fa.flash_attention(q, k, v, block_q=32, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)


def test_gradients_match_reference():
    q, k, v = _rand(s=64, d=16)

    def loss_flash(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, causal=True,
                                          block_q=32, block_k=32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3,
                                   err_msg=f"d{name} mismatch")


def test_gradients_with_bias():
    q, k, v = _rand(s=64, d=16)
    bias = jnp.where(jnp.arange(64)[None, :] < 48, 0.0, -1e9)

    gf = jax.grad(lambda a, b, c: jnp.sum(
        fa.flash_attention(a, b, c, key_bias=bias, block_q=32, block_k=32) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: jnp.sum(_ref(a, b, c, key_bias=bias) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3,
                                   err_msg=f"d{name} mismatch")


def test_attention_layer_uses_flash():
    """layers.attention with use_flash must agree with the XLA path."""
    import paddle_tpu as pt
    from paddle_tpu.layers import attention as A
    q, k, v = _rand(b=2, h=4, s=64, d=16)
    out_x = A.scaled_dot_product_attention(q, k, v, causal=True, use_flash=False)
    out_f = A.scaled_dot_product_attention(q, k, v, causal=True, use_flash=True)
    np.testing.assert_allclose(np.asarray(out_x), np.asarray(out_f), atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------------
# v2: segment ids, pallas backward, ragged shapes, lse merging


def _ref_seg(q, k, v, seg_q, seg_k, causal=False):
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    mask = seg_q[:, None, :, None] == seg_k[:, None, None, :]
    s = jnp.where(mask, s, -1e30)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        s = jnp.where(cm, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    # rows with every key masked -> zero them like the kernel does
    allmask = jnp.all(s <= -1e29, axis=-1, keepdims=True)
    p = jnp.where(allmask, 0.0, p)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def test_segment_ids_match_reference():
    q, k, v = _rand(b=2, s=128, d=32, seed=3)
    seg = jnp.asarray(np.repeat([[0, 1, 2, 3]], 32, axis=1).reshape(1, 128)
                      .repeat(2, axis=0))
    out = fa.flash_attention(q, k, v, segment_ids=seg, block_q=64, block_k=64)
    ref = _ref_seg(q, k, v, seg, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_segment_ids_causal_grads():
    q, k, v = _rand(b=1, s=128, d=32, seed=4)
    seg = jnp.asarray(np.repeat([0, 1], 64).reshape(1, 128))

    def loss_f(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, causal=True, segment_ids=seg,
                                          block_q=64, block_k=64) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(_ref_seg(q, k, v, seg, seg, causal=True) ** 2)

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-3)


def test_non_divisible_seq_pads():
    q, k, v = _rand(b=1, h=1, s=100, d=32, sk=84, seed=5)
    out = fa.flash_attention(q, k, v, block_q=64, block_k=64)
    ref = _ref(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    g = jax.grad(lambda q, k, v: jnp.sum(
        fa.flash_attention(q, k, v, block_q=64, block_k=64) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(_ref(q, k, v) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-3)


def test_many_k_blocks_streams():
    """seq >> block: K/V streamed across many grid steps (the VMEM-ceiling
    fix) — numerics must still match the dense reference."""
    q, k, v = _rand(b=1, h=1, s=64, d=32, sk=1024, seed=6)
    out = fa.flash_attention(q, k, v, block_q=64, block_k=128)
    ref = _ref(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_return_lse_matches_logsumexp():
    q, k, v = _rand(b=1, h=1, s=64, d=32, seed=7)
    out, lse = fa.flash_attention(q, k, v, block_q=32, block_k=32, return_lse=True)
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    np.testing.assert_allclose(np.asarray(lse),
                               np.asarray(jax.scipy.special.logsumexp(s, axis=-1)),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)


def test_key_bias_grads_pallas_backward():
    q, k, v = _rand(b=2, s=96, d=32, seed=8)
    bias = jnp.asarray(np.where(np.arange(96) < 70, 0.0, -1e30)[None]
                       .repeat(2, axis=0).astype(np.float32))

    def loss_f(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, key_bias=bias,
                                          block_q=32, block_k=32) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(_ref(q, k, v, key_bias=bias) ** 2)

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-3)


def test_causal_bottom_right_alignment_decode():
    """sq < sk causal (decode suffix): last query sees all keys —
    bottom-right alignment, matching the XLA fallback convention."""
    q, k, v = _rand(b=1, h=1, s=32, d=32, sk=128, seed=9)
    out = fa.flash_attention(q, k, v, causal=True, block_q=32, block_k=64)
    ref = _ref(q, k, v, causal=True)  # _ref uses tril(k=sk-sq)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_kv_segment_ids_requires_query_ids():
    from paddle_tpu.core.errors import EnforceError

    q, k, v = _rand(s=64, d=32)
    seg = jnp.zeros((1, 64), jnp.int32)
    with pytest.raises(EnforceError):
        fa.flash_attention(q, k, v, kv_segment_ids=seg)


def test_dense_mask_fallback_keeps_bias_and_segments():
    q, k, v = _rand(b=1, h=2, s=64, d=32, seed=10)
    dense = jnp.zeros((1, 2, 64, 64), jnp.float32)  # not key-bias-reducible
    bias = jnp.asarray(np.where(np.arange(64) < 40, 0.0, -1e30)[None].astype(np.float32))
    out = fa.flash_attention(q, k, v, attn_mask=dense, key_bias=bias)
    ref = _ref(q, k, v, key_bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


# -- mixed-precision backward of the dense (XLA) attention path --------------


def test_scores_mxu_bf16_grads_close_to_f32():
    """The bf16-cotangent backward (ops/attention_scores.scores_mxu)
    must stay within bf16 rounding of the exact f32 gradient."""
    from paddle_tpu.ops.attention_scores import scores_mxu as _scores_mxu

    q, k, v = _rand(b=2, h=2, s=32, d=16, seed=3)

    def loss_via(score_fn, q, k):
        s = score_fn(q, k)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v) ** 2)

    scale = 1.0 / math.sqrt(q.shape[-1])
    exact = lambda q, k: jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    mxu = lambda q, k: _scores_mxu(q, k, scale)

    qb, kb = q.astype(jnp.bfloat16), k.astype(jnp.bfloat16)
    gq_ref, gk_ref = jax.grad(lambda a, b: loss_via(exact, a, b), (0, 1))(q, k)
    gq, gk = jax.grad(lambda a, b: loss_via(mxu, a, b), (0, 1))(qb, kb)
    np.testing.assert_allclose(np.asarray(gq, np.float32), np.asarray(gq_ref),
                               rtol=0.05, atol=0.05)
    np.testing.assert_allclose(np.asarray(gk, np.float32), np.asarray(gk_ref),
                               rtol=0.05, atol=0.05)
    # f32 inputs take the same path with zero rounding change
    gq32, gk32 = jax.grad(lambda a, b: loss_via(mxu, a, b), (0, 1))(q, k)
    np.testing.assert_allclose(np.asarray(gq32), np.asarray(gq_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gk32), np.asarray(gk_ref), rtol=1e-5)


def test_dense_attention_backward_has_no_f32_dots():
    """Regression pin for the MXU-rate bug the custom VJP fixes: a bf16
    SDPA train step must lower with every dot's inputs in bf16."""
    from op_test import find_dots
    from paddle_tpu.layers.attention import scaled_dot_product_attention

    q, k, v = _rand(b=2, h=2, s=32, d=16)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))

    def loss(q, k, v):
        return jnp.sum(scaled_dot_product_attention(q, k, v, causal=True) ** 2)

    txt = jax.jit(jax.grad(loss, (0, 1, 2))).lower(qb, kb, vb).as_text()
    dots = [d[1:3] for d in find_dots(txt) if d[0] == "dot_general"]
    assert len(dots) >= 4, f"regex no longer matches dot_general ops: {len(dots)}"
    bad = [d for d in dots if d[0].endswith('f32') and d[1].endswith('f32')]
    assert not bad, f"f32xf32 dots in attention backward: {bad}"


def test_bf16_kernel_close_to_f32_reference():
    """bf16 operands now feed the kernel dots directly (MXU-native);
    fwd and grads must stay within bf16 rounding of the f32 reference."""
    q, k, v = _rand(b=1, h=2, s=96, d=32, seed=7)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))

    out = fa.flash_attention(qb, kb, vb, causal=True, block_q=32, block_k=32)
    ref = _ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=0.05, atol=0.05)

    def loss_f(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, causal=True,
                                          block_q=32, block_k=32) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(_ref(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_f, (0, 1, 2))(qb, kb, vb)
    gr = jax.grad(loss_r, (0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b),
                                   rtol=0.1, atol=0.1)


def test_block_shape_flags_resolve():
    """block_q/block_k=None resolve the flash_block_* config flags (a
    microbench sweep winner applies via PDTPU_FLASH_BLOCK_* without a
    code edit); 0 means the chip-tuned defaults; explicit args always
    win. Asserts the RESOLVED values (output is block-size-invariant,
    so numerics alone cannot catch the flags being ignored)."""
    from paddle_tpu.core.config import get_flag, set_flag
    from paddle_tpu.core.errors import EnforceError

    assert fa.resolve_block_shapes(None, None) == (fa.DEFAULT_BLOCK_Q,
                                                   fa.DEFAULT_BLOCK_K)
    assert fa.resolve_block_shapes(256, None) == (256, fa.DEFAULT_BLOCK_K)
    old_q, old_k = get_flag("flash_block_q"), get_flag("flash_block_k")
    try:
        set_flag("flash_block_q", 64)
        set_flag("flash_block_k", 64)
        assert fa.resolve_block_shapes(None, None) == (64, 64)
        assert fa.resolve_block_shapes(128, 128) == (128, 128)  # args win
        # a typo'd value fails loudly, naming the flag
        set_flag("flash_block_k", 100)
        with pytest.raises(EnforceError, match="flash_block_k"):
            fa.resolve_block_shapes(None, None)
        # and the end-to-end path consumes the flag (numerics unchanged)
        set_flag("flash_block_k", 64)
        q, k, v = _rand(s=128)
        np.testing.assert_allclose(np.asarray(fa.flash_attention(q, k, v)),
                                   np.asarray(_ref(q, k, v)),
                                   atol=2e-5, rtol=2e-5)
    finally:
        set_flag("flash_block_q", old_q)
        set_flag("flash_block_k", old_k)


def test_causal_multiblock_interior_tiles():
    """seq spanning many blocks under causal: interior (fully visible)
    tiles take the mask-free fast path, diagonal tiles mask, above-
    diagonal tiles are skipped — fwd and grads must still match the
    dense reference exactly."""
    q, k, v = _rand(s=256, d=32, seed=5)

    def loss_fa(q, k, v):
        return (fa.flash_attention(q, k, v, causal=True, block_q=32,
                                   block_k=32) ** 2).sum()

    def loss_ref(q, k, v):
        return (_ref(q, k, v, causal=True) ** 2).sum()

    np.testing.assert_allclose(
        np.asarray(fa.flash_attention(q, k, v, causal=True, block_q=32,
                                      block_k=32)),
        np.asarray(_ref(q, k, v, causal=True)), atol=2e-5, rtol=2e-5)
    g1 = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)
    # decode offset: sq < sk shifts the diagonal; interior fast path
    # must respect the offset
    q2, k2, v2 = _rand(s=64, sk=256, d=32, seed=6)
    np.testing.assert_allclose(
        np.asarray(fa.flash_attention(q2, k2, v2, causal=True, block_q=32,
                                      block_k=32)),
        np.asarray(_ref(q2, k2, v2, causal=True)), atol=2e-5, rtol=2e-5)
