"""Image preprocessing utilities (python/paddle/dataset/image.py analog).

The reference shells out to cv2; here everything is pure numpy (nearest/
bilinear resize included) so the host input pipeline has no native-cv
dependency. All functions take/return HWC uint8 or float arrays like the
reference, with ``to_chw`` as the final layout flip for NCHW models.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = [
    "load_image", "load_image_bytes", "resize_short", "to_chw", "center_crop",
    "random_crop", "left_right_flip", "simple_transform", "load_and_transform",
]


def load_image_bytes(data: bytes, is_color: bool = True) -> np.ndarray:
    """Decode an image from raw bytes (PNG/JPEG via PIL when available)."""
    import io as _io
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise ImportError("load_image_bytes needs PIL for decoding") from e
    im = Image.open(_io.BytesIO(data))
    im = im.convert("RGB" if is_color else "L")
    arr = np.asarray(im)
    return arr if is_color else arr[..., None]


def load_image(path: str, is_color: bool = True) -> np.ndarray:
    with open(path, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def _resize(im: np.ndarray, oh: int, ow: int) -> np.ndarray:
    """Bilinear resize, pure numpy, HWC."""
    h, w = im.shape[:2]
    if (h, w) == (oh, ow):
        return im
    ys = np.linspace(0, h - 1, oh)
    xs = np.linspace(0, w - 1, ow)
    y0 = np.floor(ys).astype(np.int64)
    x0 = np.floor(xs).astype(np.int64)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    imf = im.astype(np.float32)
    out = (imf[y0][:, x0] * (1 - wy) * (1 - wx) + imf[y0][:, x1] * (1 - wy) * wx
           + imf[y1][:, x0] * wy * (1 - wx) + imf[y1][:, x1] * wy * wx)
    return out.astype(im.dtype) if im.dtype == np.uint8 else out


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """image.py:180 — resize so the short side equals ``size``."""
    h, w = im.shape[:2]
    if h < w:
        return _resize(im, size, int(round(w * size / h)))
    return _resize(im, int(round(h * size / w)), size)


def to_chw(im: np.ndarray, order: Tuple[int, int, int] = (2, 0, 1)) -> np.ndarray:
    """image.py:208 — HWC → CHW."""
    return np.transpose(im, order)


def center_crop(im: np.ndarray, size: int, is_color: bool = True) -> np.ndarray:
    h, w = im.shape[:2]
    y = (h - size) // 2
    x = (w - size) // 2
    return im[y:y + size, x:x + size]


def random_crop(im: np.ndarray, size: int, is_color: bool = True,
                rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    rng = rng or np.random
    h, w = im.shape[:2]
    y = rng.randint(0, h - size + 1)
    x = rng.randint(0, w - size + 1)
    return im[y:y + size, x:x + size]


def left_right_flip(im: np.ndarray, is_color: bool = True) -> np.ndarray:
    return im[:, ::-1]


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool, is_color: bool = True,
                     mean: Optional[np.ndarray] = None) -> np.ndarray:
    """image.py:310 — the standard train/eval pipeline: resize short side,
    (random|center) crop, random flip in training, CHW, mean subtract."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color)
        if np.random.randint(2):
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color)
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        im -= mean.reshape(-1, 1, 1) if mean.ndim == 1 else mean
    return im


def load_and_transform(filename: str, resize_size: int, crop_size: int,
                       is_train: bool, is_color: bool = True,
                       mean: Optional[np.ndarray] = None) -> np.ndarray:
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
