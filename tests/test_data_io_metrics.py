"""Tests for reader combinators, feeders, datasets, metrics, io —
the reader/decorator tests + metrics tests analog."""

import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import data as pdata
from paddle_tpu import io as pio
from paddle_tpu import layers as L
from paddle_tpu import metrics


def _range_reader(n):
    def reader():
        yield from range(n)
    return reader


def test_map_shuffle_chain_compose_firstn():
    r = pdata.map_readers(lambda x: x * 2, _range_reader(5))
    assert list(r()) == [0, 2, 4, 6, 8]

    r = pdata.shuffle(_range_reader(10), buf_size=10, seed=0)
    out = list(r())
    assert sorted(out) == list(range(10)) and out != list(range(10))

    r = pdata.chain(_range_reader(2), _range_reader(3))
    assert list(r()) == [0, 1, 0, 1, 2]

    r = pdata.compose(_range_reader(3), pdata.map_readers(lambda x: x + 10, _range_reader(3)))
    assert list(r()) == [(0, 10), (1, 11), (2, 12)]

    assert list(pdata.firstn(_range_reader(100), 3)()) == [0, 1, 2]


def test_buffered_and_xmap_and_cache():
    assert list(pdata.buffered(_range_reader(20), 4)()) == list(range(20))
    r = pdata.xmap_readers(lambda x: x * x, _range_reader(10), 4, 8, order=True)
    assert list(r()) == [i * i for i in range(10)]
    r = pdata.xmap_readers(lambda x: x * x, _range_reader(10), 4, 8, order=False)
    assert sorted(r()) == sorted(i * i for i in range(10))
    calls = []

    def rr():
        calls.append(1)
        yield from range(3)

    c = pdata.cache(lambda: rr())
    # note: cache wraps the creator; first iteration fills
    c_reader = pdata.cache(rr.__call__) if False else c
    assert list(c()) == [0, 1, 2]
    assert list(c()) == [0, 1, 2]
    assert len(calls) == 1


def test_batch_drop_last():
    b = pdata.batch(_range_reader(10), 4)
    assert [len(x) for x in b()] == [4, 4]
    b = pdata.batch(_range_reader(10), 4, drop_last=False)
    assert [len(x) for x in b()] == [4, 4, 2]


def test_data_feeder_shapes_dtypes():
    f = pdata.DataFeeder(["x", "y"], dtypes=["float32", "int64"])
    samples = [(np.ones(3), 1), (np.zeros(3), 0)]
    feed = f.feed(samples)
    assert feed["x"].shape == (2, 3) and feed["x"].dtype == np.float32
    assert feed["y"].shape == (2,) and feed["y"].dtype == np.int64


def test_device_feeder_prefetch():
    def batches():
        for i in range(5):
            yield {"x": np.full((2, 2), i, np.float32)}

    seen = [np.asarray(b["x"])[0, 0] for b in pdata.DeviceFeeder(batches)]
    assert seen == [0, 1, 2, 3, 4]


def test_datasets_shapes():
    x, y = next(pdata.datasets.mnist("train")())
    assert x.shape == (784,) and x.dtype == np.float32
    x, y = next(pdata.datasets.cifar10("train")())
    assert x.shape == (3 * 32 * 32,)
    x, y = next(pdata.datasets.uci_housing()())
    assert x.shape == (13,) and y.shape == (1,)
    ids, lbl = next(pdata.datasets.imdb()())
    assert ids.shape == (128,) and ids.dtype == np.int64
    src, trg, nxt = next(pdata.datasets.wmt16()())
    assert src.shape == trg.shape == nxt.shape
    dense, sparse, y = next(pdata.datasets.ctr()())
    assert dense.shape == (13,) and sparse.shape == (26,)


# -- metrics -----------------------------------------------------------------


def test_accuracy_metric_and_op():
    import jax.numpy as jnp
    logits = jnp.asarray([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    labels = jnp.asarray([[1], [0], [0]])
    acc = metrics.accuracy(logits, labels)
    np.testing.assert_allclose(float(acc), 2 / 3, rtol=1e-6)
    m = metrics.Accuracy()
    m.update(0.5, weight=10)
    m.update(1.0, weight=10)
    assert m.eval() == pytest.approx(0.75)


def test_precision_recall():
    p = metrics.Precision()
    r = metrics.Recall()
    preds = np.array([1, 1, 0, 1])
    labels = np.array([1, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    assert p.eval() == pytest.approx(2 / 3)
    assert r.eval() == pytest.approx(2 / 3)


def test_auc_perfect_and_random():
    m = metrics.Auc(num_thresholds=1000)
    labels = np.array([0] * 500 + [1] * 500)
    preds = labels * 0.8 + 0.1  # perfectly separable
    m.update(preds, labels)
    assert m.eval() > 0.99
    m2 = metrics.Auc(num_thresholds=1000)
    rng = np.random.RandomState(0)
    m2.update(rng.rand(10000), rng.randint(0, 2, 10000))
    assert abs(m2.eval() - 0.5) < 0.03


def test_auc_in_graph_stats():
    import jax.numpy as jnp
    m = metrics.Auc(num_thresholds=100)
    preds = jnp.asarray([0.9, 0.8, 0.3, 0.1])
    labels = jnp.asarray([1, 1, 0, 0])
    tp, fp = metrics.auc_stat(preds, labels, num_thresholds=100)
    m.update_stats(tp, fp)
    assert m.eval() > 0.99


def test_edit_distance():
    m = metrics.EditDistance(normalized=False)
    m.update([[1, 2, 3]], [[1, 3]])
    d, err = m.eval()
    assert d == 1.0 and err == 1.0


def test_chunk_eval():
    p, r, f1 = metrics.chunk_eval([[(0, 2, "PER")]], [[(0, 2, "PER"), (3, 5, "LOC")]])
    assert p == 1.0 and r == 0.5 and f1 == pytest.approx(2 / 3)


# -- io ----------------------------------------------------------------------


def test_save_load_persistables_roundtrip():
    import jax.numpy as jnp
    params = {"fc_0/w": jnp.ones((2, 3)), "fc_0/b": jnp.zeros(3)}
    state = {"bn/mean": jnp.full((3,), 0.5)}
    opt_state = {"step": jnp.asarray(7), "global": {"beta1_pow": jnp.asarray(0.9)},
                 "accums": {"fc_0/w": {"moment1": jnp.ones((2, 3))}}}
    with tempfile.TemporaryDirectory() as d:
        pio.save_persistables(d, params, state, opt_state, meta={"k": 1})
        p, s, o, m = pio.load_persistables(d)
        np.testing.assert_allclose(p["fc_0/w"], np.ones((2, 3)))
        np.testing.assert_allclose(s["bn/mean"], 0.5)
        assert int(o["step"]) == 7
        np.testing.assert_allclose(o["accums"]["fc_0/w"]["moment1"], 1.0)
        assert m == {"k": 1}


def test_save_load_inference_model():
    import jax
    from paddle_tpu.models import mnist as mnist_models
    prog = pt.build(mnist_models.mlp)
    x = np.random.randn(4, 784).astype(np.float32)
    y = np.zeros((4, 1), np.int64)
    params, state = prog.init(jax.random.PRNGKey(0), x, y)
    with tempfile.TemporaryDirectory() as d:
        pio.save_inference_model(d, prog, params, state, {"image": x, "label": y})
        pred = pio.load_inference_model(d)
        out = pred.run({"image": x, "label": y})
        direct, _ = prog.apply(params, state, x, y)
        np.testing.assert_allclose(np.asarray(out["logits"]), np.asarray(direct["logits"]),
                                   rtol=1e-5, atol=1e-5)
        out2 = pred.clone().run({"image": x, "label": y})
        np.testing.assert_allclose(np.asarray(out2["loss"]), np.asarray(out["loss"]), rtol=1e-6)


def test_auc_layer_pr_curve():
    """curve='PR' integrates precision over recall (auc_op PR mode) rather
    than silently returning ROC."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu.core.errors import EnforceError

    labels = np.array([0] * 50 + [1] * 50, np.int64)
    probs = np.stack([1 - (labels * 0.8 + 0.1), labels * 0.8 + 0.1], axis=1)

    def f(p, lab, curve):
        val, batch_val = metrics.auc(p, lab, curve=curve, num_thresholds=200)
        return {"v": val, "b": batch_val}

    import functools
    for curve, expect in (("PR", 1.0), ("ROC", 1.0)):
        prog = pt.build(functools.partial(f, curve=curve))
        params, state = prog.init(jax.random.PRNGKey(0), probs, labels)
        out, _ = prog.apply(params, state, probs, labels)
        assert float(out["v"]) > 0.99, (curve, float(out["v"]))
    # random scores: ROC auc ~0.5 but PR auc ~positive fraction; both finite
    rng = np.random.RandomState(0)
    p2 = rng.rand(2000)
    lab2 = np.concatenate([np.ones(200, np.int64), np.zeros(1800, np.int64)])
    probs2 = np.stack([1 - p2, p2], axis=1)
    prog = pt.build(functools.partial(f, curve="PR"))
    params, state = prog.init(jax.random.PRNGKey(0), probs2, lab2)
    out, _ = prog.apply(params, state, probs2, lab2)
    assert 0.03 < float(out["v"]) < 0.35  # near the 10% positive base rate
    with pytest.raises(EnforceError):
        pt.build(functools.partial(f, curve="XX")).init(
            jax.random.PRNGKey(0), probs, labels)


def test_persistables_bfloat16_roundtrip(tmp_path):
    """npz stores ml_dtypes extension types as void bytes; the @dtype key
    encoding must round-trip bf16 params exactly (infer-export path)."""
    import jax.numpy as jnp

    params = {"w": jnp.arange(6, dtype=jnp.bfloat16).reshape(2, 3) * 0.5,
              "nested": {"b": jnp.ones((4,), jnp.bfloat16)}}
    pio.save_persistables(str(tmp_path / "ck"), params, {})
    loaded, _, _, _ = pio.load_persistables(str(tmp_path / "ck"))
    assert loaded["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(params["w"]).view(np.uint16),
                                  loaded["w"].view(np.uint16))
    assert loaded["nested"]["b"].dtype == jnp.bfloat16


def test_persistables_at_sign_in_name(tmp_path):
    """Param names may contain '@' (reference uses @LR_DECAY_COUNTER@,
    p@GRAD); the exotic-dtype key suffix must not swallow them."""
    import jax.numpy as jnp

    params = {"@LR_DECAY_COUNTER@": np.float32(3.0),
              "x@bfloat16": np.ones((2,), np.float32),   # adversarial name
              "y@bfloat16": np.full((2,), 7, np.uint16),  # name AND dtype collide
              "z@raw": np.arange(3, dtype=np.int32),      # escape-marker name
              "real_bf16": jnp.ones((2,), jnp.bfloat16)}
    pio.save_persistables(str(tmp_path / "ck"), params, {})
    loaded, _, _, _ = pio.load_persistables(str(tmp_path / "ck"))
    assert float(loaded["@LR_DECAY_COUNTER@"]) == 3.0
    assert loaded["x@bfloat16"].dtype == np.float32
    assert loaded["y@bfloat16"].dtype == np.uint16
    np.testing.assert_array_equal(loaded["y@bfloat16"], params["y@bfloat16"])
    np.testing.assert_array_equal(loaded["z@raw"], params["z@raw"])
    assert loaded["real_bf16"].dtype == jnp.bfloat16


def test_predictor_clone_under_threads(tmp_path):
    """Clone-per-thread serving (paddle_inference_api.h:141 Clone
    semantics): 4 threads hammer clones of one Predictor concurrently;
    every result must equal the single-threaded answer."""
    import concurrent.futures

    import jax

    from paddle_tpu.models import mnist

    prog = pt.build(mnist.mlp)
    rng = np.random.RandomState(0)
    feeds = [{"image": rng.randn(8, 784).astype(np.float32),
              "label": rng.randint(0, 10, (8, 1)).astype(np.int64)}
             for _ in range(4)]
    params, state = prog.init(jax.random.PRNGKey(0), **feeds[0])
    pio.save_inference_model(str(tmp_path / "m"), prog, params, state, feeds[0])
    pred = pio.load_inference_model(str(tmp_path / "m"))
    expected = [float(pred.run(f)["loss"]) for f in feeds]

    def worker(i):
        clone = pred.clone()
        return [float(clone.run(f)["loss"]) for f in feeds for _ in range(5)]

    expected_rep = [e for e in expected for _ in range(5)]
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as ex:
        results = list(ex.map(worker, range(4)))
    for got in results:
        np.testing.assert_allclose(got, expected_rep, rtol=1e-6)


def test_predictor_aot_no_retrace(tmp_path):
    """Predictor compiles once at load; run() executes the same compiled
    executable (api_impl.cc:64 Init/Run split) — 100 calls, no tracing."""
    import jax

    from paddle_tpu.models import mnist

    prog = pt.build(mnist.mlp)
    feed = {"image": np.random.randn(8, 784).astype(np.float32),
            "label": np.random.randint(0, 10, (8, 1)).astype(np.int64)}
    params, state = prog.init(jax.random.PRNGKey(0), **feed)
    pio.save_inference_model(str(tmp_path / "m"), prog, params, state, feed)
    pred = pio.load_inference_model(str(tmp_path / "m"))
    assert type(pred._compiled).__name__ == "Compiled"  # AOT, not a jit wrapper
    outs = [pred.run(feed)["loss"] for _ in range(100)]
    assert np.allclose([float(o) for o in outs], float(outs[0]))
    clone = pred.clone()
    assert clone._compiled is pred._compiled  # Clone shares the executable
    np.testing.assert_allclose(float(clone.run(feed)["loss"]), float(outs[0]))
