"""accum_exchange="hoisted": shard_map-local gradient accumulation
with ONE pmean per optimizer step — the wire lever SCALING.md §2 names
(the default GSPMD path reduces every microbatch, pinned by
test_collective_report.test_accum_grad_exchange_is_per_microbatch).
"""

import re

import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import debugger, optimizer as opt
from paddle_tpu.core.errors import EnforceError
from paddle_tpu.debugger import _parse_hlo_collectives
from paddle_tpu.models import transformer
from paddle_tpu.parallel import DistStrategy


def _feed(bs, seq=16, vocab=64, seed=0):
    rng = np.random.RandomState(seed)
    return {"src_ids": rng.randint(3, vocab, (bs, seq)).astype(np.int32),
            "trg_ids": rng.randint(3, vocab, (bs, seq)).astype(np.int32),
            "labels": rng.randint(3, vocab, (bs, seq)).astype(np.int32)}


def _trainer(strategy, mesh=None, rules=None, fetch_list=("loss",)):
    cfg = transformer.base_config(src_vocab=64, trg_vocab=64, d_model=32,
                                  d_inner=64, num_heads=4,
                                  num_encoder_layers=2, num_decoder_layers=2,
                                  dropout=0.0)
    prog = pt.build(transformer.make_model(cfg))
    tr = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                    sharding_rules=rules, strategy=strategy,
                    fetch_list=list(fetch_list) if fetch_list else None)
    tr.startup(sample_feed=_feed(16))
    return tr


@pytest.mark.slow
def test_hoisted_accum_matches_gspmd_and_single_device():
    """Same seed, dropout 0: hoisted accumulation must reproduce the
    GSPMD accumulation path and plain single-device accumulation, step
    for step (pmean of per-shard grad sums == global mean grad)."""
    feeds = [_feed(16, seed=i) for i in range(3)]

    def run(strategy, mesh=None, rules=None):
        tr = _trainer(strategy, mesh=mesh, rules=rules)
        return [float(tr.step(f)["loss"]) for f in feeds]

    ref = run(DistStrategy(accum_steps=2))
    mesh = pt.make_mesh({"dp": 8})
    gspmd = run(DistStrategy(accum_steps=2), mesh, pt.parallel.replicated())
    hoisted = run(DistStrategy(accum_steps=2, accum_exchange="hoisted"),
                  mesh, pt.parallel.replicated())
    np.testing.assert_allclose(gspmd, ref, atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(hoisted, ref, atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_hoisted_accum_has_no_in_loop_grad_exchange():
    """The point of the mode: grad-order all-reduce bytes inside while
    bodies drop to ~nothing (vs the GSPMD path where they are the full
    param bytes — see the companion pin in test_collective_report)."""
    mesh = pt.make_mesh({"dp": 8})
    tr = _trainer(DistStrategy(accum_steps=4, accum_exchange="hoisted"),
                  mesh, pt.parallel.replicated())
    feed = _feed(32)  # accum 4 x dp 8 shards
    hlo = debugger._lower_step(tr, feed).compile().as_text()
    bodies = set(re.findall(r"body=%?([\w.\-]+)", hlo))
    in_body = 0.0
    for block in re.split(r"\n(?=[%\w].*\{)", hlo):
        name = re.match(r"%?([\w.\-]+)", block.split("\n", 1)[0].lstrip())
        if name and name.group(1) in bodies:
            in_body += sum(p for kind, p, _ in
                           _parse_hlo_collectives(block,
                                                  fallback_group_size=8)
                           if kind == "all-reduce")
    param_bytes = sum(v.size * 4 for v in jax.tree.leaves(tr.scope.params))
    assert in_body < 0.05 * param_bytes, (
        f"{in_body:.0f}B of all-reduce inside loop bodies — the hoisted "
        "mode is not hoisting")
    # and the exchange still exists somewhere (once, outside the loop)
    total = sum(p for kind, p, _ in
                _parse_hlo_collectives(hlo, fallback_group_size=8)
                if kind == "all-reduce")
    assert total > 0.5 * param_bytes, "grad exchange disappeared entirely"


def test_hoisted_accum_preconditions_enforced():
    mesh = pt.make_mesh({"dp": 4, "fsdp": 2})
    with pytest.raises(EnforceError, match="fully replicated"):
        _trainer(DistStrategy(accum_steps=2, accum_exchange="hoisted"),
                 mesh, pt.parallel.fsdp(min_size_to_shard=64))
    with pytest.raises(EnforceError, match="needs a mesh"):
        _trainer(DistStrategy(accum_steps=2, accum_exchange="hoisted"))
    with pytest.raises(EnforceError, match="gspmd.hoisted"):
        _trainer(DistStrategy(accum_steps=2, accum_exchange="typo"),
                 pt.make_mesh({"dp": 8}), pt.parallel.replicated())
    # the knob must never be a silent no-op (typo'd mode or hoisted
    # without an accumulation loop fail even at accum_steps=1)
    with pytest.raises(EnforceError, match="gspmd.hoisted"):
        _trainer(DistStrategy(accum_exchange="hoist"),
                 pt.make_mesh({"dp": 8}), pt.parallel.replicated())
    with pytest.raises(EnforceError, match="no loop to hoist"):
        _trainer(DistStrategy(accum_exchange="hoisted"),
                 pt.make_mesh({"dp": 8}), pt.parallel.replicated())
    # per-sample / integer outputs cannot be replicated across shards:
    # without fetch_list pruning, the logits leaf fails loudly
    with pytest.raises(EnforceError, match="float scalar outputs"):
        tr = _trainer(DistStrategy(accum_steps=2,
                                   accum_exchange="hoisted"),
                      pt.make_mesh({"dp": 8}), pt.parallel.replicated(),
                      fetch_list=None)
        tr.step(_feed(16))


@pytest.mark.slow
def test_hoisted_accum_composes_with_loss_scaling():
    """bf16 AMP + dynamic loss scaling over the hoisted path: the
    scaled loss is computed inside the shard_map microbatch loop (ls
    enters via closure), grads unscale outside, and the overflow-skip
    machinery sees the pmean'd grads — training stays finite and the
    scale is reported."""
    feeds = [_feed(16, seed=i) for i in range(4)]
    mesh = pt.make_mesh({"dp": 8})
    with pt.amp_guard("bfloat16"):
        tr = _trainer(DistStrategy(accum_steps=2,
                                   accum_exchange="hoisted",
                                   dynamic_loss_scale=True),
                      mesh, pt.parallel.replicated())
        losses = [float(tr.step(f)["loss"]) for f in feeds]
    assert all(np.isfinite(l) for l in losses), losses
    out = tr.step(feeds[0])
    assert "loss_scale" in out and float(out["loss_scale"]) > 0
