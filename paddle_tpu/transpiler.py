"""Transpiler-surface compatibility (python/paddle/fluid/transpiler/).

The reference's transpilers are *program rewriters*: DistributeTranspiler
splits one program into trainer/pserver pairs (distribute_transpiler.py:240),
memory_optimize reuses variable storage via liveness analysis
(memory_optimization_transpiler.py:112). In the TPU-native design those
rewrites collapse into sharding + compiler decisions (SURVEY §7):

- parameter-server sharding  → fsdp/ep axes in `parallel.sharding` rules
  (optimizer state sharded across devices = pserver param slices),
- trainer/pserver program split → single SPMD program under pjit,
- memory optimization → XLA buffer reuse + `donate_argnums` +
  `DistStrategy.remat`.

This module keeps the reference API shape so fluid-style driver code
ports mechanically: the transpile step *produces the strategy objects*
the Trainer consumes instead of rewritten programs.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from .parallel.strategy import DistStrategy


class PSDispatcher:
    """Parameter placement policy over pserver endpoints / shard owners
    (ps_dispatcher.py). In the TPU build the 'endpoints' are positions on
    the fsdp/ep mesh axis; the dispatcher decides which shard owns each
    (split of a) parameter."""

    def __init__(self, eplist: List):
        self._eplist = list(eplist)
        self._step = 0

    def reset(self):
        self._step = 0

    def dispatch(self, varlist: List) -> List:
        raise NotImplementedError


class RoundRobin(PSDispatcher):
    """ps_dispatcher.py RoundRobin: cycle parameters over shard owners."""

    def dispatch(self, varlist: List) -> List:
        out = []
        for _ in varlist:
            out.append(self._eplist[self._step % len(self._eplist)])
            self._step += 1
        return out


class HashName(PSDispatcher):
    """ps_dispatcher.py HashName: stable name-hash placement (the
    reference hashes the variable name so placement survives restarts)."""

    def dispatch(self, varlist: List) -> List:
        import hashlib

        def _stable_hash(v):
            name = v if isinstance(v, str) else getattr(v, "name", str(v))
            # builtin hash() is salted per process; placement must survive
            # restarts (checkpoint shards follow it)
            return int(hashlib.md5(name.encode()).hexdigest(), 16)

        return [self._eplist[_stable_hash(v) % len(self._eplist)] for v in varlist]


@dataclasses.dataclass
class DistributeTranspilerConfig:
    """distribute_transpiler.py:127 analog. slice_var_up/min_block_size
    governed pserver param slicing — here they map to whether params are
    sharded (fsdp) or replicated."""

    slice_var_up: bool = True
    split_method: type = RoundRobin
    min_block_size: int = 8192
    sync_mode: bool = True


class DistributeTranspiler:
    """DistributeTranspiler API shape (distribute_transpiler.py:147).

    transpile() records the cluster layout; get_trainer_program /
    get_pserver_program return the SAME program plus a DistStrategy —
    under SPMD collectives there is no trainer/pserver program split, the
    param-shard capability is carried by fsdp/ep sharding rules
    (DESIGN.md N20-N21,N26-N27). Driver code keeps its structure;
    the executor consumes (program, strategy, mesh_axes)."""

    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self.trainer_id = 0
        self.trainers = 1
        self._program = None

    def transpile(self, trainer_id: int, program=None, pservers: str = "",
                  trainers: int = 1, sync_mode: bool = True, startup_program=None):
        self.sync_mode = bool(sync_mode and self.config.sync_mode)
        self.trainer_id = trainer_id
        self.trainers = trainers
        self._program = program
        self.pserver_endpoints = [ep for ep in pservers.split(",") if ep]

    def _strategy(self) -> DistStrategy:
        s = DistStrategy()
        # pserver param slicing capability → shard params+opt state (fsdp)
        if self.config.slice_var_up:
            s.reduce_strategy = "sharded"
        # async mode (listen_and_serv RunAsyncLoop): barrier-free push/pull
        # through the C++ pserver (parallel.async_ps) instead of SPMD
        # collectives — the strategy records it so the driver routes the
        # program to AsyncPSTrainer
        s.async_mode = not getattr(self, "sync_mode", True)
        return s

    def get_trainer_program(self):
        return self._program, self._strategy()

    def get_pserver_program(self, endpoint=None):
        # sync mode: param shards are mesh-resident; the 'pserver program'
        # is the same SPMD step restricted to its fsdp shard. async mode:
        # the pserver is the native runtime (parallel.PServerProcess) —
        # return the strategy that says so.
        return self._program, self._strategy()

    def get_startup_program(self, endpoint=None, pserver_program=None):
        return self._program, self._strategy()


def memory_optimize(input_program=None, skip_opt_set=None, print_log: bool = False,
                    level: int = 0):
    """memory_optimization_transpiler.py:456 analog. The liveness-based
    var-reuse rewrite is XLA's buffer assignment; the user-controllable
    parts are donation + rematerialization. Returns a DistStrategy with
    remat enabled — pass it to the Trainer, which flips the trace-time
    framework.remat_mode switch so zoo models' maybe_remat blocks compile
    to per-block jax.checkpoint (verify the delta with
    debugger.compiled_memory_usage)."""
    s = DistStrategy()
    s.remat = True
    return s


def release_memory(input_program=None, skip_opt_set=None):
    """release_memory analog: eager buffer release between steps is the
    runtime's job (XLA arena); kept for API parity."""
    return input_program
