"""Autodiff surface (python/paddle/fluid/backward.py).

The reference's append_backward (backward.py:469) rewrites the program:
reverse-walks ops, asks each C++ GradOpDescMaker for grad ops, sums
duplicated outputs, prunes no-grad branches. Under tracing all of that
is jax.grad; these wrappers keep the (loss, parameter_list) →
[(param, grad)] API so optimizer-driver code ports directly.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax

from .framework import Program


def append_backward(program: Program, loss_name: str = "loss",
                    parameter_list: Optional[Sequence[str]] = None,
                    no_grad_set: Optional[set] = None) -> Callable:
    """Returns grad_fn(params, state, *args) → (loss, [(name, grad)]),
    the param_grads list the reference returns. ``parameter_list`` /
    ``no_grad_set`` restrict differentiation like backward.py:469's
    arguments (stop-gradient pruning = jax's lazy evaluation of unused
    cotangents)."""

    def grad_fn(params: Dict, state: Dict, *args, **kwargs):
        names = list(parameter_list) if parameter_list is not None else list(params.keys())
        if no_grad_set:
            names = [n for n in names if n not in no_grad_set]
        wrt = {n: params[n] for n in names}
        rest = {n: v for n, v in params.items() if n not in wrt}

        def loss_of(wrt_params):
            out, _ = program.apply({**rest, **wrt_params}, state, *args, **kwargs)
            loss = out[loss_name] if isinstance(out, dict) else out
            return loss

        loss, grads = jax.value_and_grad(loss_of)(wrt)
        return loss, [(n, grads[n]) for n in names]

    return grad_fn


def calc_gradient(program: Program, target_name: str,
                  input_names: Sequence[str]) -> Callable:
    """backward.py:685 calc_gradient analog: d(target)/d(inputs) for
    non-parameter inputs. Returns grad_fn(params, state, feed_dict) →
    dict of gradients keyed by input name."""

    def grad_fn(params: Dict, state: Dict, feed: Dict):
        wrt = {n: feed[n] for n in input_names}
        rest = {n: v for n, v in feed.items() if n not in wrt}

        def target_of(wrt_feed):
            out, _ = program.apply(params, state, **{**rest, **wrt_feed})
            t = out[target_name] if isinstance(out, dict) else out
            return t.sum()

        return jax.grad(target_of)(wrt)

    return grad_fn
