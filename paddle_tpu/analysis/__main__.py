"""CLI: lint a zoo model's program before it ever compiles.

    python -m paddle_tpu.analysis --model mnist
    python -m paddle_tpu.analysis --model moe_transformer --amp bfloat16 \
        --mesh fsdp=8 --rules fsdp --fail-on warning --format json
    python -m paddle_tpu.analysis --model gpt --amp bfloat16 --ci \
        --baseline tools/analysis_baseline.json
    python -m paddle_tpu.analysis --wire-table          # markdown
    python -m paddle_tpu.analysis --wire-table --format json

Exit status (CI contract, also the ``tools/lint_gate.py`` contract):

- **0** — clean at ``--fail-on`` (default ``warning``); under ``--ci``,
  no finding whose fingerprint is absent from ``--baseline``.
- **1** — findings present (new findings under ``--ci``), each printed
  with its stable fingerprint so the failing PR can name what changed.
- **3** — the checker itself crashed (import error, trace explosion,
  bad baseline file). Distinct from 1 so CI can tell "your change
  introduced a finding" from "the checker is broken" — a crash must
  never read as a lint pass OR as the PR author's finding. (2 is
  argparse's usage-error exit, left untouched.)
"""

from __future__ import annotations

import argparse
import json
import sys
import traceback


def _usage_error(msg: str) -> "SystemExit":
    """A bad flag VALUE is a usage error — exit 2, argparse's own code,
    never 1 (findings) or 3 (checker crash)."""
    print(msg, file=sys.stderr)
    return SystemExit(2)


def _parse_mesh(spec: str):
    from ..parallel import make_mesh
    axes = {}
    for part in spec.split(","):
        k, _, v = part.partition("=")
        axes[k.strip()] = int(v)
    return make_mesh(axes)


def _parse_rules(name: str):
    from ..parallel import fsdp, replicated, transformer_tp_rules
    table = {"replicated": replicated, "fsdp": fsdp,
             "tp": transformer_tp_rules}
    if name not in table:
        raise _usage_error(f"--rules must be one of {sorted(table)}")
    return table[name]()


def _parse_severity(pairs):
    from .report import SEVERITIES

    overrides = {}
    for pair in pairs or ():
        code, sep, sev = pair.partition("=")
        if not sep:
            raise _usage_error(
                f"--severity takes code=level (e.g. moe:capacity=error), "
                f"got {pair!r}")
        sev = sev.strip()
        if sev not in SEVERITIES:
            # reject here, BEFORE the model build: a typo'd level must
            # be exit 2, not a paid-for exit-3 "checker crashed"
            raise _usage_error(
                f"--severity level must be one of {SEVERITIES}, "
                f"got {sev!r}")
        overrides[code.strip()] = sev
    return overrides


EXIT_CLEAN, EXIT_FINDINGS, EXIT_INTERNAL = 0, 1, 3


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="static jaxpr-level lint of a model-zoo program")
    ap.add_argument("--model", default="",
                    help="zoo model: mnist | transformer | moe_transformer | gpt")
    ap.add_argument("--wire-table", action="store_true",
                    help="print the framed-verb wire-contract table "
                         "extracted from both sides of every surface "
                         "(markdown; --format json for the raw rows) "
                         "and exit — no model build")
    ap.add_argument("--variant", default="",
                    help="model variant (mnist: mlp|conv; "
                         "moe_transformer: tight)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=16)
    ap.add_argument("--mesh", default="",
                    help='mesh axes, e.g. "dp=4,tp=2" (needs that many devices)')
    ap.add_argument("--rules", default="",
                    help="sharding preset: replicated | fsdp | tp")
    ap.add_argument("--amp", default="",
                    help="lint under this compute dtype (e.g. bfloat16)")
    ap.add_argument("--loss-name", default="loss")
    ap.add_argument("--select", default="",
                    help="comma-list restricting rule families, e.g. "
                         '"pipeline,collective" (default: all)')
    ap.add_argument("--pp-microbatches", type=int, default=0,
                    help="lint this pipeline schedule shape "
                         "(pipeline:* family) against --batch / --mesh")
    ap.add_argument("--pp-interleave", type=int, default=1)
    ap.add_argument("--num-epochs", type=int, default=0,
                    help="fit epochs the program will run (arms the "
                         "feed:cacheable-dataset rule with "
                         "--dataset-batches/--cache-budget-mb)")
    ap.add_argument("--dataset-batches", type=int, default=0,
                    help="batches per epoch, for the dataset's wire-byte "
                         "total")
    ap.add_argument("--cache-budget-mb", type=float, default=0.0,
                    help="residual-HBM budget for the device dataset "
                         "cache, in MB (explicit here — the CLI has no "
                         "live trainer to estimate the step's appetite)")
    ap.add_argument("--fail-on", default="warning",
                    choices=("info", "warning", "error"),
                    help="exit 1 when findings at/above this severity exist")
    ap.add_argument("--level", default="info",
                    choices=("info", "warning", "error"),
                    help="minimum severity to print")
    ap.add_argument("--format", default="text",
                    choices=("text", "json", "sarif"))
    ap.add_argument("--severity", action="append", metavar="CODE=LEVEL",
                    help="override a finding code's (or whole family's) "
                         "severity, e.g. --severity moe:capacity=error; "
                         "repeatable")
    ap.add_argument("--baseline", default="",
                    help="baseline suppression file: fingerprints listed "
                         "there never fail the run")
    ap.add_argument("--write-baseline", default="", metavar="PATH",
                    help="write the run's findings as a new baseline file "
                         "and exit 0 (freeze today's findings as accepted)")
    ap.add_argument("--ci", action="store_true",
                    help="gate mode: fail (exit 1) only on findings NOT in "
                         "--baseline, printing each new fingerprint")
    ap.add_argument("--subject", default="",
                    help="baseline subject key (default: "
                         "model[.variant][.amp] — the tools/lint_gate.py "
                         "naming, so its committed baseline suppresses "
                         "CLI runs of the same config)")
    args = ap.parse_args(argv)
    overrides = _parse_severity(args.severity)

    if args.wire_table:
        # pure source extraction — no model build, no jax: still "the
        # checker ran", so a scraper crash is exit 3
        try:
            from .wire_contracts import render_verb_table_md, verb_table
            rows = verb_table()
            if args.format == "json":
                print(json.dumps(rows, indent=1))
            else:
                print(render_verb_table_md(rows))
        except Exception:
            traceback.print_exc()
            print("analysis: internal error (exit 3) — the checker "
                  "crashed; this is NOT a lint verdict", file=sys.stderr)
            return EXIT_INTERNAL
        return EXIT_CLEAN
    if not args.model:
        raise _usage_error("--model is required (or use --wire-table)")

    from .report import (apply_severity, load_baseline, new_findings,
                         to_sarif, write_baseline)

    # everything from here is "the checker ran": a crash is exit 3, not
    # a finding verdict — argparse usage errors above stay exit 2
    try:
        from . import check
        from .zoo import build_model

        # the subject scopes baseline keys: it must match what
        # tools/lint_gate.py names the same config ("gpt.amp") or the
        # committed baseline can never suppress a CLI run of it
        subject = args.subject or (
            args.model + (f".{args.variant}" if args.variant else "")
            + (".amp" if args.amp else ""))
        program, feed = build_model(args.model, args.variant, args.batch,
                                    args.seq)
        mesh = _parse_mesh(args.mesh) if args.mesh else None
        rules = _parse_rules(args.rules) if args.rules else None
        strategy = None
        if args.pp_microbatches:
            from ..parallel import DistStrategy
            strategy = DistStrategy(pp_microbatches=args.pp_microbatches,
                                    pp_interleave=args.pp_interleave)
        select = ({s.strip() for s in args.select.split(",") if s.strip()}
                  or None)
        report = check(program, feed, mesh=mesh, rules=rules,
                       strategy=strategy, amp=args.amp or None,
                       loss_name=args.loss_name, select=select,
                       num_epochs=args.num_epochs or None,
                       dataset_batches=args.dataset_batches or None,
                       cache_budget_bytes=(int(args.cache_budget_mb * 1e6)
                                           if args.cache_budget_mb else None))
        apply_severity(report, overrides)

        if args.write_baseline:
            doc = write_baseline(args.write_baseline, [(subject, report)])
            print(f"wrote baseline {args.write_baseline} "
                  f"({len(doc['baseline'])} suppressed fingerprints)")
            return EXIT_CLEAN

        baseline = load_baseline(args.baseline or None)
        fresh = new_findings(subject, report, baseline, args.fail_on)

        if args.format == "json":
            print(json.dumps(report.to_dict(), indent=1, default=str))
        elif args.format == "sarif":
            print(json.dumps(to_sarif([(subject, report)]), indent=1))
        else:
            print(report.render(args.level))
        if (args.ci or args.baseline) and fresh:
            # stderr: stdout stays machine-parseable under json/sarif
            print(f"{len(fresh)} new finding(s) vs baseline "
                  f"{args.baseline or '<empty>'}:", file=sys.stderr)
            for f in fresh:
                print(f"  {f.fingerprint}", file=sys.stderr)
    except Exception:
        # NOT BaseException: SystemExit keeps its own code and a ^C
        # (KeyboardInterrupt, conventional 130) must stay a cancelled
        # run, never read as "the checker is broken"
        traceback.print_exc()
        print("analysis: internal error (exit 3) — the checker crashed; "
              "this is NOT a lint verdict", file=sys.stderr)
        return EXIT_INTERNAL

    # --baseline honors its promise ("fingerprints listed there never
    # fail the run") with or without --ci
    if args.ci or args.baseline:
        return EXIT_FINDINGS if fresh else EXIT_CLEAN
    return EXIT_CLEAN if report.ok(args.fail_on) else EXIT_FINDINGS


if __name__ == "__main__":
    sys.exit(main())
