// Python-free native trainer over the PJRT C API.
//
// Full capability parity with the reference's C++ training entry
// (train/demo/demo_trainer.cc: load a saved program + params, drive the
// epoch loop, track loss — no Python in the process; the reference's
// demo loads a ProgramDesc into its C++ Executor). Our training
// artifact (io.py save_train_artifact) is one jitted optimizer STEP
// exported as StableHLO:
//
//   step(params..., opt_state..., state..., seed, feeds...)
//       -> (params'..., opt_state'..., state'..., loss)
//
// with the first num_carry outputs positionally aligned to the first
// num_carry inputs (both flatten dicts in sorted-key order), so the
// C++ loop is pure buffer plumbing: execute, swap the carry buffers to
// the outputs, restage the seed scalar, repeat. The training loop,
// batch feeding, loss tracking, and the convergence check all live
// here; XLA owns the math.
//
//   trainer <artifact_dir> <pjrt_plugin.so> [--probe] [--steps N]
//
// --probe stops after the accelerator-free half: artifact
// load/validation (meta_train.json vs npz shapes/dtypes + carry
// alignment) and the plugin version handshake. The full run trains on
// the exported example batch (feed_<name>.npy) until the loss drops —
// overfitting one batch is the convergence check that needs no
// task-specific data generator and works for ANY exported program.
//
// Build (test_native_trainer.py does this):
//   g++ -O2 -std=c++17 -I$TF_INCLUDE trainer.cc -o trainer -ldl

#include "pjrt_common.h"

namespace {

// meta_train.json is the predictor meta plus {"num_carry": N}; pull the
// integer out with the same minimal scanning ParseMetaInputs uses.
size_t ParseNumCarry(const std::string& js) {
  size_t k = js.find("\"num_carry\"");
  if (k == std::string::npos) Die("meta_train.json missing num_carry");
  k = js.find(':', k);
  return strtoull(js.c_str() + k + 1, nullptr, 10);
}

}  // namespace

int main(int argc, char** argv) {
  g_tool = "trainer";
  if (argc < 3) {
    fprintf(stderr,
            "usage: trainer <artifact_dir> <pjrt_plugin.so> [--probe] "
            "[--steps N]\n");
    return 2;
  }
  std::string dir = argv[1], plugin = argv[2];
  bool probe = false;
  long steps = 30;
  for (int i = 3; i < argc; ++i) {
    if (std::string(argv[i]) == "--probe") probe = true;
    if (std::string(argv[i]) == "--steps" && i + 1 < argc)
      steps = strtol(argv[++i], nullptr, 10);
  }

  // ---- artifact load + validation (no accelerator needed) ---------------
  std::string mlir = ReadFileOrDie(dir + "/train_step.mlir");
  std::string meta = ReadFileOrDie(dir + "/meta_train.json");
  std::string params_blob = ReadFileOrDie(dir + "/params.npz");
  std::string opt_blob = ReadFileOrDie(dir + "/opt.npz");
  std::string state_blob = ReadFileOrDie(dir + "/state.npz");
  auto params = ParseNpz(params_blob, "params.npz");
  auto opt = ParseNpz(opt_blob, "opt.npz");
  std::map<std::string, Array> state;
  if (state_blob.size() > 4 && rd32(state_blob.data()) == 0x04034b50)
    state = ParseNpz(state_blob, "state.npz");
  auto inputs = ParseMetaInputs(meta);
  size_t num_carry = ParseNumCarry(meta);
  if (num_carry == 0 || num_carry >= inputs.size())
    Die("num_carry " + std::to_string(num_carry) + " out of range for " +
        std::to_string(inputs.size()) + " inputs");

  size_t feed_args = 0, weight_bytes = 0;
  bool saw_seed = false;
  for (size_t i = 0; i < inputs.size(); ++i) {
    const auto& sp = inputs[i];
    DType dt = DtypeOrDie(sp.dtype);
    size_t want = dt.size;
    for (int64_t d : sp.shape) want *= size_t(d);
    if (sp.source == "seed") {
      if (i != num_carry) Die("seed input must sit right after the carry");
      saw_seed = true;
      continue;
    }
    if (sp.source == "feed") {
      if (i < num_carry) Die("feed input inside the carry prefix");
      ++feed_args;
      continue;
    }
    if (i >= num_carry) Die("weight input past the carry prefix: " + sp.name);
    auto& table = sp.source == "params.npz" ? params
                  : sp.source == "opt.npz"  ? opt
                                            : state;
    auto it = table.find(sp.name);
    if (it == table.end())
      Die("meta input " + sp.name + " missing from " + sp.source);
    const Array& got = it->second;
    if (got.nbytes != want || got.dtype != dt.npy || got.shape != sp.shape)
      Die("weight " + sp.name + " does not match the exported signature");
    weight_bytes += want;
  }
  if (!saw_seed) Die("meta_train.json has no seed input");
  fprintf(stderr,
          "trainer: artifact ok — %zu args (%zu carry %.1f MB, %zu feeds), "
          "stablehlo %zu bytes\n",
          inputs.size(), num_carry, weight_bytes / 1048576.0, feed_args,
          mlir.size());

  // ---- plugin handshake -------------------------------------------------
  void* lib = dlopen(plugin.c_str(), RTLD_NOW | RTLD_LOCAL);
  if (!lib) Die(std::string("dlopen failed: ") + dlerror());
  auto get_api =
      reinterpret_cast<const PJRT_Api* (*)()>(dlsym(lib, "GetPjrtApi"));
  if (!get_api) Die("plugin has no GetPjrtApi symbol");
  g_api = get_api();
  if (!g_api) Die("GetPjrtApi returned null");
  fprintf(stderr, "trainer: plugin PJRT API v%d.%d (header v%d.%d)\n",
          g_api->pjrt_api_version.major_version,
          g_api->pjrt_api_version.minor_version, PJRT_API_MAJOR,
          PJRT_API_MINOR);
  if (g_api->pjrt_api_version.major_version != PJRT_API_MAJOR)
    Die("PJRT major version mismatch");

  if (probe) {
    printf("PROBE OK\n");
    return 0;
  }

  PJRT_Plugin_Initialize_Args pi;
  memset(&pi, 0, sizeof pi);
  pi.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
  Check(g_api->PJRT_Plugin_Initialize(&pi), "plugin init");

  PJRT_Client_Create_Args cc;
  memset(&cc, 0, sizeof cc);
  cc.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  Check(g_api->PJRT_Client_Create(&cc), "client create");
  PJRT_Client* client = cc.client;

  PJRT_Client_AddressableDevices_Args ad;
  memset(&ad, 0, sizeof ad);
  ad.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
  ad.client = client;
  Check(g_api->PJRT_Client_AddressableDevices(&ad), "devices");
  if (ad.num_addressable_devices == 0) Die("no addressable devices");
  PJRT_Device* dev = ad.addressable_devices[0];

  // ---- compile ----------------------------------------------------------
  PJRT_Program prog;
  memset(&prog, 0, sizeof prog);
  prog.struct_size = PJRT_Program_STRUCT_SIZE;
  prog.code = mlir.data();
  prog.code_size = mlir.size();
  static const char kFmt[] = "mlir";
  prog.format = kFmt;
  prog.format_size = 4;
  std::string copts = MinimalCompileOptions();
  PJRT_Client_Compile_Args comp;
  memset(&comp, 0, sizeof comp);
  comp.struct_size = PJRT_Client_Compile_Args_STRUCT_SIZE;
  comp.client = client;
  comp.program = &prog;
  comp.compile_options = copts.data();
  comp.compile_options_size = copts.size();
  Check(g_api->PJRT_Client_Compile(&comp), "compile");
  fprintf(stderr, "trainer: train step compiled\n");

  auto stage = [&](const char* data, const InputSpec& sp) -> PJRT_Buffer* {
    DType dt = DtypeOrDie(sp.dtype);
    PJRT_Client_BufferFromHostBuffer_Args hb;
    memset(&hb, 0, sizeof hb);
    hb.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
    hb.client = client;
    hb.data = data;
    hb.type = dt.pjrt;
    hb.dims = sp.shape.data();
    hb.num_dims = sp.shape.size();
    hb.host_buffer_semantics =
        PJRT_HostBufferSemantics_kImmutableUntilTransferCompletes;
    hb.device = dev;
    Check(g_api->PJRT_Client_BufferFromHostBuffer(&hb),
          ("h2d " + sp.name).c_str());
    AwaitAndDestroy(hb.done_with_host_buffer, "h2d done");
    return hb.buffer;
  };

  // ---- stage initial carry + fixed feeds --------------------------------
  std::vector<PJRT_Buffer*> args(inputs.size(), nullptr);
  std::vector<std::string> feed_storage;
  uint32_t seed_host = 0;
  size_t seed_idx = num_carry;
  for (size_t i = 0; i < inputs.size(); ++i) {
    const auto& sp = inputs[i];
    if (sp.source == "seed") {
      args[i] = stage(reinterpret_cast<const char*>(&seed_host), sp);
    } else if (sp.source == "feed") {
      std::string path = dir + "/feed_" + sp.name + ".npy";
      std::string blob = ReadFileOrDie(path);
      feed_storage.push_back(std::move(blob));
      Array a = ParseNpy(feed_storage.back().data(),
                         feed_storage.back().size(), path);
      DType dt = DtypeOrDie(sp.dtype);
      if (a.dtype != dt.npy || a.shape != sp.shape)
        Die("feed " + sp.name + " does not match the exported signature");
      args[i] = stage(a.data, sp);
    } else {
      auto& table = sp.source == "params.npz" ? params
                    : sp.source == "opt.npz"  ? opt
                                              : state;
      args[i] = stage(table.at(sp.name).data, sp);
    }
  }

  // ---- the training loop (demo_trainer.cc's epoch loop) -----------------
  PJRT_LoadedExecutable_GetExecutable_Args ge;
  memset(&ge, 0, sizeof ge);
  ge.struct_size = PJRT_LoadedExecutable_GetExecutable_Args_STRUCT_SIZE;
  ge.loaded_executable = comp.executable;
  Check(g_api->PJRT_LoadedExecutable_GetExecutable(&ge), "get executable");
  PJRT_Executable_NumOutputs_Args no;
  memset(&no, 0, sizeof no);
  no.struct_size = PJRT_Executable_NumOutputs_Args_STRUCT_SIZE;
  no.executable = ge.executable;
  Check(g_api->PJRT_Executable_NumOutputs(&no), "num outputs");
  if (no.num_outputs != num_carry + 1)
    Die("executable has " + std::to_string(no.num_outputs) +
        " outputs, expected carry+loss = " + std::to_string(num_carry + 1));

  double first_loss = 0, last_loss = 0;
  for (long step = 0; step < steps; ++step) {
    std::vector<PJRT_Buffer*> outs(no.num_outputs, nullptr);
    PJRT_Buffer** out_list = outs.data();
    PJRT_Buffer* const* arg_list = args.data();
    PJRT_ExecuteOptions eo;
    memset(&eo, 0, sizeof eo);
    eo.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;
    PJRT_Event* done = nullptr;
    PJRT_LoadedExecutable_Execute_Args ex;
    memset(&ex, 0, sizeof ex);
    ex.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
    ex.executable = comp.executable;
    ex.options = &eo;
    ex.argument_lists = &arg_list;
    ex.num_devices = 1;
    ex.num_args = args.size();
    ex.output_lists = &out_list;
    ex.device_complete_events = &done;
    ex.execute_device = dev;
    Check(g_api->PJRT_LoadedExecutable_Execute(&ex), "execute");
    AwaitAndDestroy(done, "execute done");

    // loss is the final output — a f32 scalar
    PJRT_Buffer_ToHostBuffer_Args th;
    memset(&th, 0, sizeof th);
    th.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
    th.src = outs[num_carry];
    Check(g_api->PJRT_Buffer_ToHostBuffer(&th), "d2h size query");
    std::vector<char> host(th.dst_size);
    th.dst = host.data();
    Check(g_api->PJRT_Buffer_ToHostBuffer(&th), "d2h");
    AwaitAndDestroy(th.event, "d2h done");
    float loss = 0;
    if (host.size() >= 4) memcpy(&loss, host.data(), 4);
    if (step == 0) first_loss = loss;
    last_loss = loss;
    printf("STEP %ld LOSS %.6f\n", step, loss);

    // swap: outputs become next step's carry; old carry buffers retire
    for (size_t i = 0; i < num_carry; ++i) {
      PJRT_Buffer_Destroy_Args bd;
      memset(&bd, 0, sizeof bd);
      bd.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
      bd.buffer = args[i];
      Check(g_api->PJRT_Buffer_Destroy(&bd), "carry destroy");
      args[i] = outs[i];
    }
    PJRT_Buffer_Destroy_Args ld;
    memset(&ld, 0, sizeof ld);
    ld.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
    ld.buffer = outs[num_carry];
    Check(g_api->PJRT_Buffer_Destroy(&ld), "loss destroy");

    // restage the per-step RNG seed
    PJRT_Buffer_Destroy_Args sd;
    memset(&sd, 0, sizeof sd);
    sd.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
    sd.buffer = args[seed_idx];
    Check(g_api->PJRT_Buffer_Destroy(&sd), "seed destroy");
    seed_host = uint32_t(step + 1);
    args[seed_idx] = stage(reinterpret_cast<const char*>(&seed_host),
                           inputs[seed_idx]);
  }

  if (!(last_loss < first_loss)) {
    fprintf(stderr, "trainer: loss did not drop (%.6f -> %.6f)\n", first_loss,
            last_loss);
    return 1;
  }
  printf("TRAIN OK first=%.6f last=%.6f\n", first_loss, last_loss);
  return 0;
}
