"""Quantization + low-precision inference rewrites.

Analog of the reference's program-rewrite family:
- ``contrib/quantize/quantize_transpiler.py`` (INT8 QAT: insert
  fake-quant/dequant ops around weights/activations),
- ``paddle/contrib/float16/float16_transpiler.py`` (fp16 inference
  rewrite),
- ``transpiler/inference_transpiler.py`` (BN folding).

Here the rewrites operate on the *function/params* level instead of a
ProgramDesc: fake-quant is a straight-through-estimator op usable inside
any layer composition (QAT), and post-training quantization transforms
the params pytree (per-channel int8 weights + scales) with a
dequantizing wrapper for inference. bf16/f16 inference = params cast +
amp_guard (the float16_transpiler capability).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, jax.Array]


# -- fake quantization (QAT, quantize_transpiler analog) ---------------------


@jax.custom_vjp
def fake_quant(x, scale, num_bits=8):
    qmax = 2.0 ** (num_bits - 1) - 1
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)
    return q * scale / qmax


def _fq_fwd(x, scale, num_bits=8):
    return fake_quant(x, scale, num_bits), (x, scale, num_bits)


def _fq_bwd(res, g):
    x, scale, num_bits = res
    qmax = 2.0 ** (num_bits - 1) - 1
    # straight-through: pass grads where un-clipped (fake_quantize_abs_max grad)
    mask = (jnp.abs(x / scale) <= 1.0).astype(g.dtype)
    return g * mask, None, None


fake_quant.defvjp(_fq_fwd, _fq_bwd)


def fake_quant_abs_max(x, num_bits=8):
    """fake_quantize_abs_max op analog: dynamic per-tensor scale."""
    scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    return fake_quant(x, scale, num_bits)


def quant_dequant_moving_avg(x, state_scale, decay=0.9, num_bits=8):
    """fake_quantize_moving_average_abs_max analog; returns (out,
    new_scale) — thread new_scale through framework state."""
    cur = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    new_scale = decay * state_scale + (1 - decay) * cur
    return fake_quant(x, new_scale, num_bits), new_scale


# -- post-training quantization (PTQ) ---------------------------------------


def quantize_params(params: Params, num_bits: int = 8,
                    predicate: Optional[Callable[[str, jax.Array], bool]] = None,
                    ) -> Tuple[Dict[str, Any], Dict[str, jax.Array]]:
    """Per-channel symmetric int8 quantization of weight matrices/filters.

    Returns (quantized_store, dequantized_params_fn input): the store
    maps name -> {'q': int8 array, 'scale': per-out-channel scales} for
    selected params and passes others through. ~4x checkpoint shrink —
    the reference's INT8 deployment capability."""
    if predicate is None:
        predicate = lambda name, v: name.endswith("/w") and v.ndim >= 2
    qmax = 2.0 ** (num_bits - 1) - 1
    store: Dict[str, Any] = {}
    for name, v in params.items():
        if predicate(name, v):
            red = tuple(range(1, v.ndim))
            scale = jnp.maximum(jnp.max(jnp.abs(v), axis=red), 1e-8)
            sshape = (v.shape[0],) + (1,) * (v.ndim - 1)
            q = jnp.clip(jnp.round(v / scale.reshape(sshape) * qmax), -qmax, qmax
                         ).astype(jnp.int8)
            store[name] = {"q": q, "scale": scale}
        else:
            store[name] = v
    return store


def dequantize_params(store: Dict[str, Any], dtype=jnp.float32) -> Params:
    """Expand a quantized store back to dense params for inference."""
    qmax_for = lambda q: 2.0 ** (8 - 1) - 1
    out: Params = {}
    for name, v in store.items():
        if isinstance(v, dict) and "q" in v:
            q, scale = v["q"], v["scale"]
            sshape = (q.shape[0],) + (1,) * (q.ndim - 1)
            out[name] = (q.astype(jnp.float32) * scale.reshape(sshape) / qmax_for(q)
                         ).astype(dtype)
        else:
            out[name] = v
    return out


# -- real int8 compute (serving) ---------------------------------------------
#
# Unlike dequantize_params (weight-compression parity: int8 storage,
# bf16 math), these run the matmul/conv itself in int8×int8→int32 — the
# datapath the reference's INT8 deployment ran through MKL-DNN/TensorRT,
# here hitting the TPU MXU's int8 mode (2× bf16 peak on v5e-class
# chips). Activations are quantized dynamically per tensor, weights per
# output channel, inside the graph, so the exported serving program is
# self-contained (no calibration pass needed; abs-max scaling).

_int8_mode = threading.local()


@contextlib.contextmanager
def int8_serving(enabled: bool = True):
    """Trace-time switch: layers' fc/conv2d matmuls run as dynamic int8
    while active. Wrap the *trace* (build/export/jit) of an inference
    program::

        with quantize.int8_serving():
            io.save_inference_model(dir, model, params, state, feed)

    The quantization ops are baked into the traced program, so the
    loaded Predictor serves int8 with no flag set."""
    old = getattr(_int8_mode, "on", False)
    _int8_mode.on = bool(enabled)
    try:
        yield
    finally:
        _int8_mode.on = old


def in_int8_serving() -> bool:
    return getattr(_int8_mode, "on", False)


def _quant_dynamic(x, axes, qmax=127.0):
    """Symmetric abs-max quantization over ``axes`` → (int8, scale)."""
    scale = jnp.maximum(jnp.max(jnp.abs(x), axis=axes, keepdims=True), 1e-8)
    scale = scale.astype(jnp.float32)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale * qmax),
                 -qmax, qmax).astype(jnp.int8)
    return q, scale


def int8_dynamic_matmul(x, w):
    """``x @ w`` with per-tensor dynamic activation quant and
    per-out-channel weight quant in int8 (int32 accumulation)."""
    qmax = 127.0
    out_dtype = jnp.promote_types(x.dtype, w.dtype)
    xq, sx = _quant_dynamic(x, axes=tuple(range(x.ndim)))
    wq, sw = _quant_dynamic(w, axes=(0,))  # [1, n] per out column
    acc = jax.lax.dot_general(
        xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * (sx * sw / (qmax * qmax))).astype(out_dtype)


def int8_dynamic_conv(x, w, window_strides, padding, rhs_dilation,
                      dimension_numbers, feature_group_count=1):
    """conv_general_dilated in int8: per-tensor activation scale,
    per-out-channel filter scale (re-applied along the output feature
    dim)."""
    qmax = 127.0
    out_dtype = jnp.promote_types(x.dtype, w.dtype)
    xq, sx = _quant_dynamic(x, axes=tuple(range(x.ndim)))
    dn = dimension_numbers
    oc_axis = dn.rhs_spec[0]  # output-channel axis of the filter
    wq, sw = _quant_dynamic(w, axes=tuple(a for a in range(w.ndim)
                                          if a != oc_axis))
    acc = jax.lax.conv_general_dilated(
        xq, wq, window_strides=window_strides, padding=padding,
        rhs_dilation=rhs_dilation, dimension_numbers=dn,
        feature_group_count=feature_group_count,
        preferred_element_type=jnp.int32)
    # broadcast the per-channel scale along the output's feature axis
    sw_vec = sw.reshape(-1)
    sshape = [1] * acc.ndim
    sshape[dn.out_spec[1]] = sw_vec.shape[0]
    scale = (sx * sw_vec.reshape(sshape)) / (qmax * qmax)
    return (acc.astype(jnp.float32) * scale).astype(out_dtype)


# -- low-precision inference (float16_transpiler analog) ---------------------


def cast_params_for_inference(params: Params, dtype=jnp.bfloat16) -> Params:
    """Cast float params for low-precision inference (pair with
    framework.amp_guard for the compute side)."""
    return {k: (v.astype(dtype) if jnp.issubdtype(v.dtype, jnp.floating) else v)
            for k, v in params.items()}


# -- BN folding (inference_transpiler analog) --------------------------------


def fold_batch_norms(params: Params, state: Dict[str, jax.Array],
                     conv_bn_pairs) -> Params:
    """Fold BN(scale,bias,mean,var) into the preceding conv's weights —
    inference_transpiler.py's conv+BN fuse. ``conv_bn_pairs`` is a list
    of (conv_name, bn_name) prefixes (e.g. ("conv2d_0", "batch_norm_0"));
    the conv must be bias-free (the reference's pattern)."""
    out = dict(params)
    for conv, bn in conv_bn_pairs:
        w = params[f"{conv}/w"]
        gamma = params[f"{bn}/scale"]
        beta = params[f"{bn}/bias"]
        mean = state[f"{bn}/moving_mean"]
        var = state[f"{bn}/moving_variance"]
        inv = gamma * jax.lax.rsqrt(var + 1e-5)
        out[f"{conv}/w"] = w * inv.reshape((-1,) + (1,) * (w.ndim - 1))
        out[f"{conv}/folded_bias"] = beta - mean * inv
    return out
