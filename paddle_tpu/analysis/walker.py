"""Jaxpr walking utilities shared by the static checker and debugger.

The jaxpr is this framework's ProgramDesc (framework.py docstring), so
every analysis is some walk over it. These helpers centralize the
recursion into nested jaxprs (scan/while/cond/pjit/shard_map bodies) so
rules can reason about *where* an equation sits — e.g. "psum inside a
scan body" — the information the reference's IR passes got from block
nesting (program_desc block indices).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

# Primitive names that carry nested jaxprs whose eqns execute repeatedly
# per outer execution (loop bodies) — the contexts where a per-iteration
# collective multiplies its wire cost by the trip count.
LOOP_PRIMS = frozenset({"scan", "while"})

# Cross-device collective primitives, split by cost class: reductions
# exchange O(payload) over the whole group (the per-microbatch-allreduce
# hazard class); neighbor permutes are the deliberate building block of
# ring/pipeline schedules.
REDUCTION_COLLECTIVES = frozenset({
    "psum", "psum2", "pmax", "pmin", "all_gather", "all_to_all",
    "psum_scatter", "reduce_scatter", "pgather",
})
PERMUTE_COLLECTIVES = frozenset({"ppermute", "pbroadcast", "collective_permute"})
COLLECTIVES = REDUCTION_COLLECTIVES | PERMUTE_COLLECTIVES


def eqn_subjaxprs(eqn) -> Iterator[Any]:
    """Yield every jaxpr nested in one equation's params (scan/cond
    bodies, pjit/shard_map callees, custom_vjp branches...)."""
    for v in eqn.params.values():
        if hasattr(v, "jaxpr"):
            yield v.jaxpr
        elif hasattr(v, "eqns"):  # raw Jaxpr (not Closed)
            yield v
        elif isinstance(v, (list, tuple)):
            for u in v:
                if hasattr(u, "jaxpr"):
                    yield u.jaxpr
                elif hasattr(u, "eqns"):
                    yield u


def walk_jaxprs(jaxpr, visit: Callable[[Any], None]) -> None:
    """Depth-first ``visit(jaxpr)`` over a jaxpr and every nested one."""
    visit(jaxpr)
    for eqn in jaxpr.eqns:
        for sub in eqn_subjaxprs(eqn):
            walk_jaxprs(sub, visit)


def iter_eqns(jaxpr, path: Tuple[str, ...] = ()) -> Iterator[Tuple[Any, Tuple[str, ...]]]:
    """Yield ``(eqn, path)`` for every equation, ``path`` being the tuple
    of enclosing primitive names outermost-first — e.g. a psum inside the
    microbatch scan of a jitted step shows ``("pjit", "scan", "shard_map")``."""
    for eqn in jaxpr.eqns:
        yield eqn, path
        sub_path = path + (eqn.primitive.name,)
        for sub in eqn_subjaxprs(eqn):
            yield from iter_eqns(sub, sub_path)


def in_loop(path: Tuple[str, ...]) -> bool:
    return any(p in LOOP_PRIMS for p in path)


def aval_bytes(aval) -> int:
    """Byte size of an abstract value (0 for non-array avals)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(np.prod(shape or (1,))) * np.dtype(dtype).itemsize


def eqn_out_bytes(eqn) -> int:
    return sum(aval_bytes(getattr(ov, "aval", None)) for ov in eqn.outvars)


def is_literal(var) -> bool:
    return hasattr(var, "val") and not hasattr(var, "count")


def literal_value(var):
    return getattr(var, "val", None)


def producer_map(jaxpr) -> Dict[int, Any]:
    """id(outvar) → producing eqn for one jaxpr scope (no nesting)."""
    out: Dict[int, Any] = {}
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            out[id(ov)] = eqn
    return out


def used_var_ids(jaxpr) -> set:
    """ids of vars consumed anywhere in one jaxpr scope: eqn inputs and
    the jaxpr's own outputs. An invar absent from this set is dead —
    traced in but never read (make_jaxpr does not DCE invars)."""
    used = set()
    for eqn in jaxpr.eqns:
        for iv in eqn.invars:
            if not is_literal(iv):
                used.add(id(iv))
    for ov in jaxpr.outvars:
        if not is_literal(ov):
            used.add(id(ov))
    return used


def is_structural_zero(var, producers: Dict[int, Any],
                       _depth: int = 0) -> bool:
    """True when ``var`` is provably the constant 0 — a literal zero or a
    broadcast/convert/reshape chain bottoming out in one. This is exactly
    the shape jax.grad emits for a parameter the loss does not depend on,
    so it distinguishes structurally-zero gradients from merely
    data-independent ones (e.g. grad of sum(p) is a broadcast of 1.0)."""
    if _depth > 32:
        return False
    if is_literal(var):
        v = literal_value(var)
        try:
            return bool(np.all(np.asarray(v) == 0))
        except Exception:
            return False
    eqn = producers.get(id(var))
    if eqn is None:
        return False
    if eqn.primitive.name in ("broadcast_in_dim", "convert_element_type",
                              "reshape", "transpose", "mul", "neg"):
        # mul: 0 * anything stays 0 (one zero operand suffices)
        if eqn.primitive.name == "mul":
            return any(is_structural_zero(iv, producers, _depth + 1)
                       for iv in eqn.invars)
        return is_structural_zero(eqn.invars[0], producers, _depth + 1)
    return False
