"""DeepFM CTR trainer over the async parameter server — the dist_ctr.py
workload shape (reference: tests/unittests/dist_ctr.py driven by
test_dist_base.py): N barrier-free trainer processes, each on its own
data shard, pushing sparse-model gradients into one C++ pserver.

    python async_ps_ctr_runner.py <trainer_id> <ps_port> <epochs> [--compress]

Importable by the convergence test for the shared model/data config
(CFG/DATA) and batch helper, so the sync baseline trains the identical
model on the identical rows.
"""

import os
import sys

import numpy as np

# tiny DeepFM: every structural piece of the BASELINE config (sparse FM
# first/second order, deep tower, dense linear) at test scale
CFG = dict(num_sparse_fields=6, sparse_feature_dim=50, embedding_size=8,
           num_dense=13, hidden_dims=(32, 32))
DATA = dict(num_sparse_fields=6, sparse_dim=50, synthetic_size=1536)
LR = 0.3
BS = 64


def make_prog():
    import paddle_tpu as pt
    from paddle_tpu.models import deepfm
    return pt.build(deepfm.make_model(**CFG))


def ctr_batches(split, shard=0, nshards=1):
    """Materialized feed dicts for one shard of the ctr reader."""
    from paddle_tpu.data import datasets
    rows = list(datasets.ctr(split, **DATA)())[shard::nshards]
    out = []
    for i in range(0, len(rows) - BS + 1, BS):
        chunk = rows[i:i + BS]
        out.append({
            "dense": np.stack([r[0] for r in chunk]),
            "sparse_ids": np.stack([r[1] for r in chunk]),
            "label": np.stack([r[2] for r in chunk]).reshape(-1, 1),
        })
    return out


def main():
    pid, port, epochs = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    compress = "--compress" in sys.argv
    from paddle_tpu.parallel import AsyncPSTrainer

    prog = make_prog()
    feeds = ctr_batches("train", shard=pid, nshards=2)
    t = AsyncPSTrainer(prog, ("127.0.0.1", port), trainer_id=pid,
                       pull_interval=2, fetch_list=["loss"],
                       compress_grads=compress)
    t.startup(sample_feed=feeds[0])
    for e in range(epochs):
        for b in feeds:
            out = t.step(b)
        print(f"LOSS {e} {float(out['loss']):.6f}", flush=True)
    print("DONE", flush=True)


if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    main()
