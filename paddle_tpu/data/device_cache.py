"""HBM dataset cache: keep the encoded epoch on device, re-feed it
device-to-device.

The bench story for two rounds has been "compute is fine, the h2d link
is the wall" (BENCH r05: resnet50 19.9 img/s delivered vs 2174
compute-only over a 53 MB/s link). PR 4's wire formats shrank the bytes
(≥3.5×); this module stops RE-SENDING them: epoch 1 streams normally
but retains each encoded (wire-format, pre-decode) chunk on device;
epoch 2+ feeds the fused step device-to-device with ZERO h2d wire
bytes. The cache stores exactly what crossed the link — the uint8/bf16
wire arrays, pre-decode — so the step program's fused decode (and the
on-device augmentation appended to it, :mod:`.augment`) runs unchanged
and a cached epoch is bit-identical to a streamed one.

**Admission** is budgeted against residual HBM: the device budget
minus the PR 6 advisor's estimate of the step's appetite (params + opt
state + backward-held activations), times a safety margin. The cache
degrades gracefully:

- dataset fits → **full**: epoch 2+ never touches the link;
- budget runs out mid-epoch → **partial**: the admitted PREFIX serves
  from HBM, the rest streams (admission stops at the first rejection so
  the cached region is a contiguous prefix — the replay order question
  never arises);
- no budget at all (CPU backend with no explicit budget, or residual
  ≤ 0) → **off**: every epoch streams, nothing else changes.

**Sharded caches store each replica's shard only**: the cached values
are the ``jax.Array``\\ s ``put_batch`` produced, already laid out by
the batch sharding — per-device residency is the shard, not the global
batch, and the budget accounting reads per-device bytes off the
addressable shards.

**Invalidation**: ``fit(resume=True)`` and
``resilience.reshard_restore`` (elastic rejoin) invalidate through
``trainer.device_cache`` — a resumed run lands mid-epoch (the cached
prefix no longer aligns with what the epoch will consume) and a
resharded trainer has a NEW mesh (the cached arrays' shardings belong
to the old one). The cache assumes an epoch-stable reader (same batches
in the same order each epoch — the contract ``pt.data.reader.cache``
documents); a per-epoch-shuffled reader would be silently replayed in
epoch-1 order, so don't cache one (MIGRATION.md "Device-resident data
path").
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np


def _log():
    return logging.getLogger("paddle_tpu.device_cache")


def device_feed_nbytes(feed: Dict[str, Any]) -> int:
    """Total bytes of the device arrays in a feed dict — the wire bytes
    a streamed transfer of the same chunk would have moved (device
    arrays hold the ENCODED wire dtype; the decode is traced into the
    step)."""
    import jax

    total = 0
    for v in feed.values():
        if isinstance(v, jax.Array):
            total += int(np.prod(v.shape or (1,))) * np.dtype(v.dtype).itemsize
        else:
            total += np.asarray(v).nbytes
    return total


def device_feed_resident_nbytes(feed: Dict[str, Any]) -> int:
    """Per-DEVICE resident bytes of a feed dict: the max over devices of
    the addressable shard bytes living there. For a replicated array
    every device holds a full copy (counts full size); for a
    batch-sharded array each device holds 1/N (counts the shard) — the
    honest number to charge against a per-device HBM budget."""
    import jax

    per_dev: Dict[Any, int] = {}
    for v in feed.values():
        if not isinstance(v, jax.Array):
            per_dev[None] = per_dev.get(None, 0) + np.asarray(v).nbytes
            continue
        try:
            shards = v.addressable_shards
        except Exception:
            per_dev[None] = per_dev.get(None, 0) + int(
                np.prod(v.shape or (1,))) * np.dtype(v.dtype).itemsize
            continue
        for s in shards:
            b = int(np.prod(s.data.shape or (1,))) \
                * np.dtype(s.data.dtype).itemsize
            per_dev[s.device] = per_dev.get(s.device, 0) + b
    return max(per_dev.values()) if per_dev else 0


def residual_hbm_bytes(trainer, sample_feed: Dict[str, Any],
                       safety: float = 0.8,
                       hbm_budget_bytes: Optional[int] = None
                       ) -> Optional[int]:
    """The advisor's estimate of the HBM left over after the train step
    (params + opt state + backward-held activations): the dataset
    cache's automatic admission budget. ``None`` when the backend
    exposes no memory budget (CPU) and no explicit
    ``hbm_budget_bytes`` is given. ``safety`` discounts the device
    budget the same way the advisor's over-budget check does, so the
    cache never admits into the step's own headroom."""
    from ..profiling.advisor import device_hbm_bytes, memory_estimate

    budget = (hbm_budget_bytes if hbm_budget_bytes is not None
              else device_hbm_bytes(
                  trainer.mesh.devices.flat[0] if trainer.mesh is not None
                  else trainer.place.device()))
    if budget is None:
        return None
    est = memory_estimate(trainer, sample_feed, project_remat=False)
    used = est["est_total_bytes"]
    return max(0, int(safety * budget) - int(used))


class DeviceCache:
    """The HBM dataset cache ``fit(device_cache=...)`` drives: epoch 1
    offers each transferred chunk; epoch 2+ serves the admitted prefix
    device-to-device. Thread-compatible with the DeviceFeeder story —
    offers/serves happen on the training-loop thread only; the lock
    guards cross-thread stat reads (telemetry scrapes).

    States (``.state``): ``"cold"`` (nothing offered yet),
    ``"admitting"`` (epoch 1 in flight), ``"full"`` / ``"partial"``
    (sealed; epoch 2+ serves), ``"off"`` (no budget or budget
    exhausted before the first chunk), ``"invalid"`` (explicitly
    invalidated — reload/reshard)."""

    def __init__(self, budget_bytes: Optional[int] = None,
                 trainer=None, safety: float = 0.8):
        self.budget_bytes = budget_bytes   # None -> resolve at first offer
        self.safety = float(safety)
        self._trainer = trainer
        self._lock = threading.Lock()
        self._chunks: List[Tuple[int, Dict[str, Any], int]] = []  # (n, feed, wire_b)
        self._resident = 0          # per-device bytes admitted
        self._rejected = False      # first rejection ends admission (prefix)
        self._sealed = False
        self._complete = False      # sealed covering the WHOLE epoch
        self._invalid_reason: Optional[str] = None
        self._off_reason: Optional[str] = None
        self.hits = 0
        self.hit_bytes = 0

    # -- construction --------------------------------------------------------
    @classmethod
    def make(cls, obj, trainer=None) -> Optional["DeviceCache"]:
        """Normalize ``fit(device_cache=...)``: ``None``/``False`` →
        no cache; ``True``/``"auto"`` → advisor-budgeted; an int → that
        explicit per-device byte budget; a DeviceCache → itself (bound
        to the trainer)."""
        if obj is None or obj is False:
            return None
        if isinstance(obj, cls):
            obj._trainer = trainer if trainer is not None else obj._trainer
            return obj
        if obj is True or obj == "auto":
            return cls(trainer=trainer)
        if isinstance(obj, (int, np.integer)):
            return cls(budget_bytes=int(obj), trainer=trainer)
        raise TypeError(
            f"device_cache: expected None|bool|'auto'|int budget|"
            f"DeviceCache, got {type(obj).__name__}")

    def bind(self, trainer) -> "DeviceCache":
        self._trainer = trainer
        return self

    # -- state ---------------------------------------------------------------
    def _state_locked(self) -> str:
        if self._invalid_reason is not None:
            return "invalid"
        if self._off_reason is not None:
            return "off"
        if self._sealed:
            return "full" if self._complete else "partial"
        return "admitting" if self._chunks or self._rejected else "cold"

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    @property
    def ready(self) -> bool:
        """Sealed with at least one chunk: epoch 2+ can serve."""
        with self._lock:
            return (self._sealed and bool(self._chunks)
                    and self._invalid_reason is None)

    @property
    def complete(self) -> bool:
        """Sealed AND covering the whole epoch (zero streaming left)."""
        return self.ready and self._complete

    @property
    def cached_steps(self) -> int:
        """Optimizer steps (== reader batches) the cached prefix
        covers."""
        with self._lock:
            return sum(n for n, _, _ in self._chunks)

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident

    def _resolve_budget(self, n: int, device_feed) -> Optional[int]:
        if self.budget_bytes is not None:
            return self.budget_bytes
        if self._trainer is None:
            return None
        try:
            import jax

            # the advisor traces the STEP, so it needs per-step avals:
            # a fused chunk carries (K, batch, ...) — slice the K axis
            # off as shape/dtype structs (no device work)
            sample = {
                k: jax.ShapeDtypeStruct(
                    tuple(v.shape[1:] if n > 1 else v.shape),
                    np.dtype(v.dtype))
                for k, v in device_feed.items()}
            self.budget_bytes = residual_hbm_bytes(
                self._trainer, sample, safety=self.safety)
        except Exception as e:
            _log().warning("device cache: residual-HBM estimate failed "
                           "(%s: %s); cache off", type(e).__name__, e)
            self.budget_bytes = None
        return self.budget_bytes

    # -- epoch-1 admission ---------------------------------------------------
    def offer(self, n: int, device_feed: Dict[str, Any]) -> bool:
        """Offer one transferred chunk (``n`` steps of device-resident
        encoded feed) for admission. Returns True when retained. The
        first rejection permanently ends admission so the cached region
        is a contiguous epoch prefix."""
        with self._lock:
            if (self._sealed or self._rejected
                    or self._invalid_reason is not None
                    or self._off_reason is not None):
                return False
        budget = self._resolve_budget(n, device_feed)
        if budget is None:
            with self._lock:
                reason = "no HBM budget (CPU backend? pass " \
                         "an explicit device_cache byte budget)"
                self._off_reason = reason
            _log().info("device cache off: %s", reason)
            return False
        per_dev = device_feed_resident_nbytes(device_feed)
        wire_b = device_feed_nbytes(device_feed)
        with self._lock:
            if self._resident + per_dev > budget:
                self._rejected = True
                if not self._chunks:
                    self._off_reason = (
                        f"first chunk ({per_dev} B/device) exceeds the "
                        f"{budget} B residual-HBM budget")
                return False
            self._chunks.append((int(n), device_feed, wire_b))
            self._resident += per_dev
            return True

    def seal(self, epoch_steps: int) -> None:
        """End of a fully-observed epoch 1: freeze the cache.
        ``epoch_steps`` is the epoch's true step count — equal to the
        cached prefix means the whole dataset is resident (full);
        greater means partial."""
        with self._lock:
            if self._invalid_reason is not None or not self._chunks:
                return
            self._sealed = True
            complete = (not self._rejected
                        and sum(n for n, _, _ in self._chunks)
                        == int(epoch_steps))
            self._complete = complete
        _log().info(
            "device cache sealed: %s, %d steps / %d bytes resident per "
            "device", "full" if complete else "partial",
            self.cached_steps, self.resident_bytes)

    # -- epoch-2+ serving ----------------------------------------------------
    def chunks(self, metrics=None) -> Iterator[Tuple[int, Dict[str, Any]]]:
        """Yield the cached prefix as ``(n, device_feed)`` chunks —
        zero h2d bytes. ``metrics`` (a ``PipelineMetrics``) records each
        hit's wire bytes under the cache attribution (never the h2d
        stage)."""
        with self._lock:
            snapshot = list(self._chunks) if self._sealed \
                and self._invalid_reason is None else []
        for n, feed, wire_b in snapshot:
            with self._lock:
                self.hits += 1
                self.hit_bytes += wire_b
            if metrics is not None:
                metrics.record_cache_hit(wire_b)
            yield n, feed

    # -- invalidation --------------------------------------------------------
    def invalidate(self, reason: str) -> None:
        """Drop every cached chunk (HBM released as soon as the step
        stops referencing them). Called on checkpoint reload and
        elastic reshard — and safe to call any time; a later fit
        streams and re-admits from scratch via :meth:`reset`."""
        with self._lock:
            had = bool(self._chunks)
            self._chunks = []
            self._resident = 0
            self._sealed = self._complete = False
            self._rejected = False
            self._invalid_reason = str(reason)
        if had:
            _log().info("device cache invalidated (%s)", reason)

    def reset(self) -> None:
        """Clear an invalidation so a fresh epoch can re-admit."""
        with self._lock:
            self._invalid_reason = None
            self._off_reason = None
            self._chunks = []
            self._resident = 0
            self._sealed = self._complete = False
            self._rejected = False

    @property
    def invalid_reason(self) -> Optional[str]:
        return self._invalid_reason

    def report(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state_locked(),
                "budget_bytes": self.budget_bytes,
                "resident_bytes": self._resident,
                "cached_steps": sum(n for n, _, _ in self._chunks),
                "cached_chunks": len(self._chunks),
                "hits": self.hits,
                "hit_bytes": self.hit_bytes,
                "invalid_reason": self._invalid_reason,
                "off_reason": self._off_reason,
            }
