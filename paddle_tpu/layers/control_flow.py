"""Control-flow ops.

Analog of python/paddle/fluid/layers/control_flow.py (While:655,
IfElse:1412, Switch:1286, StaticRNN:429, DynamicRNN:1542) and the C++
control-flow ops (while_op.cc, conditional_block_op.cc, SURVEY N17).
The reference interprets sub-blocks with nested executors; here the
same capabilities are thin, jit-safe wrappers over lax.while_loop /
cond / switch — XLA compiles the loop body once (no per-iteration
interpreter). StaticRNN/DynamicRNN live in layers.rnn (scan-based).
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence

import jax
import jax.numpy as jnp


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Any):
    """While analog (control_flow.py:655 / while_op.cc): loop_vars is a
    pytree; cond_fn -> bool scalar; body_fn -> new pytree."""
    return jax.lax.while_loop(cond_fn, body_fn, loop_vars)


def cond(pred, true_fn: Callable, false_fn: Callable, *operands):
    """conditional_block/IfElse analog. Both branches are traced (XLA
    select), matching the reference's requirement that both blocks exist."""
    return jax.lax.cond(pred, true_fn, false_fn, *operands)


def case(pred_fn_pairs: Sequence, default: Callable = None):
    """layers.case analog: first true predicate wins."""
    preds = [p for p, _ in pred_fn_pairs]
    fns = [f for _, f in pred_fn_pairs]
    if default is not None:
        fns = fns + [default]
    # index of first true pred, else len(preds) (default)
    stacked = jnp.stack([jnp.asarray(p, jnp.bool_) for p in preds])
    first = jnp.argmax(stacked)
    any_true = jnp.any(stacked)
    idx = jnp.where(any_true, first, len(preds) if default is not None else 0)
    return jax.lax.switch(idx, fns)


def switch_case(branch_index, branch_fns: Sequence[Callable], default: Callable = None):
    """switch/case analog (control_flow.py Switch:1286)."""
    fns = list(branch_fns)
    if default is not None:
        n = len(fns)
        idx = jnp.clip(branch_index, 0, n)
        idx = jnp.where((branch_index >= 0) & (branch_index < n), branch_index, n)
        return jax.lax.switch(idx, fns + [default])
    return jax.lax.switch(jnp.clip(branch_index, 0, len(fns) - 1), fns)


def Print(x, message: str = "", summarize: int = 20, name=None):
    """In-graph Print op analog (control_flow.py:146) via jax.debug."""
    jax.debug.print(message + " {}", x)
    return x


def array_write(arr, i, x):
    """LoDTensorArray write analog: arr is a preallocated [cap, ...]
    buffer (static capacity — the TPU-native design)."""
    return jax.lax.dynamic_update_index_in_dim(arr, x, i, axis=0)


def array_read(arr, i):
    return jax.lax.dynamic_index_in_dim(arr, i, axis=0, keepdims=False)


def create_array(capacity: int, element_shape, dtype=jnp.float32):
    return jnp.zeros((capacity,) + tuple(element_shape), dtype)


def increment(x, value=1, in_place=None):
    return x + value


def less_than(x, y, force_cpu=None):
    return jnp.less(x, y)


def array_length(arr):
    return jnp.asarray(arr.shape[0])


class While:
    """Class-form While (control_flow.py While:655) over the functional
    while_loop: ``While(cond_fn)(body_fn, loop_vars)``. Both are pytree →
    pytree; lowers to lax.while_loop."""

    def __init__(self, cond_fn: Callable, name=None):
        self.cond_fn = cond_fn

    def __call__(self, body_fn: Callable, loop_vars):
        return while_loop(self.cond_fn, body_fn, loop_vars)


class IfElse:
    """Row-wise IfElse (control_flow.py IfElse:1412): the reference
    scatters batch rows into true/false sub-blocks and merges. Dense TPU
    lowering: both branch fns run on the full batch and rows are selected
    by the mask — identical results, MXU-friendly.

    ``IfElse(cond_rows)(true_fn, false_fn, x)`` with cond_rows [b] or
    [b,1] boolean."""

    def __init__(self, cond, name=None):
        self.cond = jnp.asarray(cond)

    def __call__(self, true_fn: Callable, false_fn: Callable, *operands):
        t = true_fn(*operands)
        f = false_fn(*operands)
        mask = self.cond.reshape(-1)

        def sel(a, b):
            m = mask.reshape((-1,) + (1,) * (a.ndim - 1))
            return jnp.where(m, a, b)

        return jax.tree.map(sel, t, f)


class Switch:
    """Scalar Switch (control_flow.py Switch:1286): ordered
    (predicate, fn) cases + default — first true wins, like the
    reference's cascade of conditional_blocks."""

    def __init__(self, name=None):
        self.cases: List = []
        self.default_fn: Callable = None

    def case(self, pred, fn: Callable):
        self.cases.append((pred, fn))
        return self

    def default(self, fn: Callable):
        self.default_fn = fn
        return self

    def __call__(self):
        return case(self.cases, self.default_fn)


class StaticRNN:
    """StaticRNN (control_flow.py:429): fixed-length scan over time.
    ``StaticRNN()(cell_fn, inputs, init_state)`` with cell_fn(state, x_t)
    → (new_state, out_t); inputs [b, t, …]. Lowers to lax.scan."""

    def __init__(self, name=None):
        pass

    def __call__(self, cell_fn: Callable, inputs, init_state):
        from .rnn import rnn as _rnn
        return _rnn(cell_fn, inputs, init_state)


class DynamicRNN:
    """DynamicRNN (control_flow.py:1542): ragged-batch scan. Same as
    StaticRNN plus per-row ``sequence_length`` masking — the
    lod_rank_table/shrink_memory machinery replaced by state masking
    (numerically equal; see layers/rnn.py docstring)."""

    def __init__(self, name=None):
        pass

    def __call__(self, cell_fn: Callable, inputs, init_state, sequence_length=None):
        from .rnn import rnn as _rnn
        return _rnn(cell_fn, inputs, init_state, sequence_length=sequence_length)
