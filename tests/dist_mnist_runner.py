"""Runnable distributed-trainer script — the dist_mnist.py analog
(SURVEY §4: model scripts driven by runtime_main in test_dist_base.py).

Launched as subprocesses by test_dist_multiprocess.py:
    python dist_mnist_runner.py <proc_id> <nprocs> <port> <steps> [mode]
mode "dp" (default): pure data parallel, one device per process.
mode "dp_fsdp": 2 virtual devices per process, mesh {dp: nprocs,
fsdp: 2} — the data axis rides the cross-process (DCN analog) dimension
while params/optimizer state shard over each process's local devices
(ICI analog); the reference's multi-node NCCL2 topology, with param
slicing.
mode "dp_hoisted": dp=2 with DistStrategy(accum_steps=2,
accum_exchange="hoisted") — the shard_map-local accumulation whose ONE
pmean per optimizer step crosses the process (DCN analog) boundary;
with nprocs=1 the same global mesh lives on 2 local devices (the
parity reference). Prints per-step losses as `LOSS <step> <value>`."""

import os
import sys

pid, nprocs, port, steps = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], int(sys.argv[4])
mode = sys.argv[5] if len(sys.argv) > 5 else "dp"
if mode not in ("dp", "dp_fsdp", "dp_hoisted"):
    sys.exit(f"unknown mode {mode!r} (dp|dp_fsdp|dp_hoisted)")
local_devices = (2 if mode == "dp_fsdp"
                 else 2 if (mode == "dp_hoisted" and nprocs == 1) else 1)
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
_flags.append(f"--xla_force_host_platform_device_count={local_devices}")
os.environ["XLA_FLAGS"] = " ".join(_flags)

import jax

jax.config.update("jax_platforms", "cpu")

if nprocs > 1:
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=nprocs, process_id=pid)

import numpy as np

import paddle_tpu as pt
from paddle_tpu import optimizer as opt
from paddle_tpu.models import mnist as mnist_models


def global_batches(step, global_bs=64):
    """Deterministic global batch for step; each process takes its slice."""
    rng = np.random.RandomState(1000 + step)
    centers = np.random.RandomState(0).randn(10, 784).astype(np.float32)
    y = rng.randint(0, 10, (global_bs,))
    x = centers[y] + 0.5 * rng.randn(global_bs, 784).astype(np.float32)
    return x, y[:, None].astype(np.int64)


def main():
    prog = pt.build(mnist_models.mlp)
    strategy = None
    fetch = None
    if mode == "dp_fsdp":
        mesh = pt.make_mesh({"dp": nprocs, "fsdp": local_devices})
        rules = pt.parallel.fsdp(min_size_to_shard=1)
    else:
        mesh = pt.make_mesh({"dp": jax.device_count()})
        rules = pt.parallel.replicated()
    if mode == "dp_hoisted":
        from paddle_tpu.parallel import DistStrategy
        strategy = DistStrategy(accum_steps=2, accum_exchange="hoisted")
        fetch = ["loss"]  # logits are per-sample: prune for the hoisted path
    trainer = pt.Trainer(prog, opt.SGD(0.1), loss_name="loss", mesh=mesh,
                         sharding_rules=rules, strategy=strategy,
                         fetch_list=fetch)
    x0, y0 = global_batches(0)
    local = x0.shape[0] // nprocs
    sample = {"image": x0[:local], "label": y0[:local]}
    trainer.startup(rng=jax.random.PRNGKey(42), sample_feed=sample)
    for s in range(steps):
        x, y = global_batches(s)
        lo, hi = pid * local, (pid + 1) * local
        out = trainer.step({"image": x[lo:hi], "label": y[lo:hi]},
                           rng=jax.random.PRNGKey(s))
        print(f"LOSS {s} {float(out['loss']):.6f}", flush=True)


if __name__ == "__main__":
    main()
