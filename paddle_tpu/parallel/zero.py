"""ZeRO-style cross-replica sharded weight update.

The weight-update sharding of "Automatic Cross-Replica Sharding of
Weight Update in Data-Parallel Training" (PAPERS.md, 2004.13336),
expressed GSPMD-style: every param / float optimizer accumulator is
flattened, zero-padded to a multiple of the data-shard count N, and
reshaped to ``(N, k)`` with the leading axis sharded over the data mesh
axes — each replica owns one ``(1, k)`` row. The optimizer update runs
on the shard rows only (optimizer HBM drops ~N×); the step all-gathers
fresh params back to logical shape at its top (``combine_params`` under
a replicated sharding constraint → one all-gather per param per step,
amortized across the fused K-step scan), and partitions the freshly
reduced gradients down to rows right before the update
(``partition_grads`` under the row constraint → GSPMD keeps only this
replica's slice of the all-reduced grad, i.e. a reduce-scatter).

Padding discipline: pad elements start at 0 and STAY 0 — gradients of
pads are 0 (they never touch the loss), every built-in optimizer maps
(p=0, g=0, acc=0) → 0, and weight decay multiplies 0. Global-norm
quantities (grad clipping, LARS trust ratios) are therefore unaffected
by pads; elementwise updates are bit-exact vs. the replicated update,
norm-coupled ones agree to float tolerance (reduction order changes).

The flat ``(N, k)`` layout (not per-dim sharding) is what makes the
checkpoint story tractable: a shard file holds one ``(k,)`` row per
leaf, and the N→M elastic restore is a concat + re-pad
(``io.load_persistables`` gathers transparently; the general
redistribution primitive is the ROADMAP ``parallel.redistribute``
follow-up, 2112.01075).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, FrozenSet, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..io import SEP, flat_spec

PARAMS_NPZ = "params.npz"
OPT_NPZ = "opt_state.npz"
STATE_NPZ = "state.npz"


@dataclasses.dataclass(frozen=True)
class ZeroSpec:
    """Static description of one trainer's ZeRO partitioning: the data
    axes and shard count, the LOGICAL flat shape/dtype spec per
    checkpoint collection (what a non-ZeRO trainer of the same model
    would save — the currency of the ``analysis.contracts`` checks and
    of ``meta.zero.arrays``), the set of flat npz keys that are
    partitioned (everything else in opt_state stays replicated), and
    per-param logical shapes/dtypes for in-step combine."""

    axes: Tuple[str, ...]
    axes_dict: Dict[str, int]
    n: int
    arrays: Dict[str, Dict[str, Dict[str, Any]]]
    partitioned: Dict[str, FrozenSet[str]]
    shapes: Dict[str, Tuple[int, ...]]
    dtypes: Dict[str, Any]


def shard_pspec(axes: Tuple[str, ...]) -> P:
    """Row-sharded PartitionSpec for the ``(N, k)`` layout."""
    return P(axes if len(axes) > 1 else axes[0], None)


def shard_sharding(mesh: Mesh, axes: Tuple[str, ...]) -> NamedSharding:
    return NamedSharding(mesh, shard_pspec(axes))


def row_size(shape, n: int) -> int:
    """k: padded per-shard row length for a logical ``shape`` at N shards."""
    size = int(np.prod(shape)) if len(shape) else 1
    return -(-size // n)


def partition_leaf(x, n: int):
    """logical leaf -> (N, k) rows, zero-padded. Traceable (used inside
    the step for gradients) and eager-safe (used at startup)."""
    size = int(np.prod(x.shape)) if x.ndim else 1
    k = -(-size // n)
    flat = jnp.ravel(x)
    if n * k != size:
        flat = jnp.pad(flat, (0, n * k - size))
    return flat.reshape(n, k)


def combine_leaf(x2, shape):
    """(N, k) rows -> logical leaf (drop padding)."""
    size = int(np.prod(shape)) if len(shape) else 1
    return x2.reshape(-1)[:size].reshape(tuple(shape))


def _opt_partitioned_keys(opt_arrays: Dict[str, Dict[str, Any]],
                          shapes: Dict[str, Tuple[int, ...]]) -> FrozenSet[str]:
    """Flat opt_state npz keys that shard: accum leaves whose logical
    shape equals their param's — mirroring ``parallel.api.shard_scope``'s
    accums-inherit-the-param-spec rule. ``step``/``global`` scalars and
    any non-param-shaped accum stay replicated."""
    out = set()
    for key, ent in opt_arrays.items():
        parts = key.split(SEP)
        if len(parts) >= 3 and parts[0] == "accums":
            shape = shapes.get(parts[1])
            if shape is not None and tuple(ent["shape"]) == shape:
                out.add(key)
    return frozenset(out)


def make_spec(mesh: Mesh, axes: Tuple[str, ...], params: Dict[str, Any],
              state: Any, opt_state: Any) -> ZeroSpec:
    """Build the ZeroSpec from LOGICAL (pre-partition) scope trees."""
    axes = tuple(axes)
    axes_dict = {a: int(mesh.shape[a]) for a in axes}
    n = int(np.prod(list(axes_dict.values())))
    shapes = {name: tuple(leaf.shape) for name, leaf in params.items()}
    dtypes = {name: jnp.dtype(leaf.dtype) for name, leaf in params.items()}
    arrays = {PARAMS_NPZ: flat_spec(params), STATE_NPZ: flat_spec(state or {}),
              OPT_NPZ: flat_spec(opt_state) if opt_state is not None else {}}
    partitioned = {
        PARAMS_NPZ: frozenset(arrays[PARAMS_NPZ]),
        STATE_NPZ: frozenset(),
        OPT_NPZ: _opt_partitioned_keys(arrays[OPT_NPZ], shapes),
    }
    return ZeroSpec(axes=axes, axes_dict=axes_dict, n=n, arrays=arrays,
                    partitioned=partitioned, shapes=shapes, dtypes=dtypes)


# -- eager placement (Trainer.startup / checkpoint restore) ------------------


def partition_params(params: Dict[str, Any], spec: ZeroSpec,
                     mesh: Mesh) -> Dict[str, Any]:
    ns = shard_sharding(mesh, spec.axes)
    return {name: jax.device_put(partition_leaf(jnp.asarray(leaf), spec.n), ns)
            for name, leaf in params.items()}


def partition_opt_state(opt_state: Any, spec: ZeroSpec, mesh: Mesh) -> Any:
    """Partition the param-shaped accum leaves; re-place everything else
    replicated. Walks ``accums`` at arbitrary depth below the param name
    (built-in optimizers keep one slot level)."""
    if opt_state is None:
        return None
    ns = shard_sharding(mesh, spec.axes)
    repl = NamedSharding(mesh, P())

    def walk(tree, shape):
        if isinstance(tree, dict):
            return {k: walk(v, shape) for k, v in tree.items()}
        if tree is None:
            return None
        if shape is not None and tuple(tree.shape) == shape:
            return jax.device_put(partition_leaf(jnp.asarray(tree), spec.n), ns)
        return jax.device_put(tree, repl)

    out = {}
    for key, sub in opt_state.items():
        if key == "accums" and isinstance(sub, dict):
            out[key] = {pname: walk(acc, spec.shapes.get(pname))
                        for pname, acc in sub.items()}
        else:
            out[key] = walk(sub, None)
    return out


# -- traced combine/partition (inside the jitted step) -----------------------


def combine_params(pshards: Dict[str, Any], spec: ZeroSpec,
                   mesh: Mesh = None) -> Dict[str, Any]:
    """Shard rows -> logical params. Under jit the replicated constraint
    makes GSPMD materialize the all-gather here — the top-of-step
    "fresh params" gather of the paper."""
    repl = NamedSharding(mesh, P()) if mesh is not None else None
    out = {}
    for name, leaf in pshards.items():
        full = combine_leaf(leaf, spec.shapes[name])
        if repl is not None:
            full = jax.lax.with_sharding_constraint(full, repl)
        out[name] = full
    return out


def partition_grads(grads: Dict[str, Any], spec: ZeroSpec,
                    mesh: Mesh = None) -> Dict[str, Any]:
    """Logical (all-reduced) grads -> shard rows. The row constraint
    tells GSPMD each replica only needs its own slice, so the grad
    exchange + slice fuses into a reduce-scatter-shaped program."""
    ns = shard_sharding(mesh, spec.axes) if mesh is not None else None
    out = {}
    for name, g in grads.items():
        g2 = partition_leaf(g, spec.n)
        if ns is not None:
            g2 = jax.lax.with_sharding_constraint(g2, ns)
        out[name] = g2
    return out


def allgather_bytes_per_step(spec: ZeroSpec) -> int:
    """Per-device wire bytes the top-of-step param all-gather moves:
    ring all-gather sends (N-1) row-sized hops per leaf per data axis."""
    total = 0
    for name, shape in spec.shapes.items():
        k = row_size(shape, spec.n)
        itemsize = jnp.dtype(spec.dtypes[name]).itemsize
        for size in spec.axes_dict.values():
            total += (size - 1) * k * itemsize
    return int(total)
