"""The bench suite's driver contract (bench.py): priority ordering,
config registry consistency, result assembly, and quick-mode overrides
— pure-Python, no device. The driver records BENCH_r{N}.json from this
machinery; a silent drift here loses the round's record."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import bench
import pytest


@pytest.fixture(autouse=True)
def _no_ambient_filter(monkeypatch):
    # a leaked BENCH_ONLY debug setting must not skew the contract tests
    monkeypatch.delenv("BENCH_ONLY", raising=False)


def test_priority_order_leads_with_baseline_configs():
    names = bench._suite_names()
    assert names[:5] == ["mnist_mlp", "resnet50", "transformer", "bert",
                         "deepfm"]
    assert names[5:8] == ["resnet50_infer_bf16", "resnet50_infer_int8",
                          "resnet50_infer_fp32"]
    assert names[8] == "gpt"
    # every registered config appears exactly once
    expect = set(bench.TRAIN_CONFIGS) | set(bench.INFER_CONFIGS) | {"gpt_decode"}
    assert set(names) == expect and len(names) == len(expect)


def test_bench_only_filter(monkeypatch):
    monkeypatch.setenv("BENCH_ONLY", "bert, gpt_decode")
    assert bench._suite_names() == ["bert", "gpt_decode"]


def test_result_key_mapping():
    assert bench._result_key("bert") == "bert_train"
    assert bench._result_key("resnet50_infer_int8") == "resnet50_infer_int8"
    assert bench._result_key("gpt_decode") == "gpt_decode"


def test_run_one_rejects_unknown_and_applies_quick_overrides(monkeypatch):
    with pytest.raises(ValueError, match="unknown config"):
        bench._run_one("nope", 1.0)
    seen = {}
    monkeypatch.setitem(bench.TRAIN_CONFIGS, "gpt_32k",
                        lambda peak, **kw: seen.update(kw) or {"v": 1})
    bench._run_one("gpt_32k", 1.0, quick=True)
    assert seen == {"iters": 2, "seq": 2048}  # QUICK_OVERRIDES applied


def test_assemble_headline_and_partial_shape():
    configs = {
        "mnist_mlp_train": {"mfu": 0.4, "value": 1.0},
        "bert_train": {"mfu": 0.55, "value": 2.0},
        "resnet50_train": {"mfu": 0.5, "value": 3.0, "vs_baseline": 24.0},
        "resnet50_infer_bf16": {"mfu": 0.9, "value": 4.0},  # infer: no headline
        "broken_train": {"error": "Timeout"},
    }
    res = bench._assemble(configs, "TPU v5 lite", 197e12, "table", "bfloat16")
    assert res["metric"] == "suite"
    assert res["value"] == 0.55          # max TRAIN mfu only
    assert res["vs_baseline"] == 24.0    # resnet50 ratio carried up
    assert res["device"] == "TPU v5 lite"
    assert res["configs"] is configs


def test_assemble_degraded_link_uses_compute_only():
    """Below LINK_DEGRADED_MBPS the pipelined numbers measure the dev
    tunnel, not the framework: the headline must switch to the
    compute-only variant, say so in the unit, and flag the record."""
    configs = {
        "bert_train": {"mfu": 0.01, "mfu_compute_only": 0.55, "value": 2.0},
        "resnet50_train": {"mfu": 0.002, "mfu_compute_only": 0.3, "value": 3.0,
                           "compute_only": 2000.0, "vs_baseline": 0.2},
    }
    res = bench._assemble(configs, "TPU v5 lite", 197e12, "table", "bfloat16",
                          h2d_mbps=12.0)
    assert res["link_degraded"] is True
    assert res["value"] == 0.55
    assert "compute-only" in res["unit"]
    assert res["vs_baseline"] == round(2000.0 / bench.BASELINES["resnet50"], 2)
    # healthy link: pipelined headline, no flag
    res2 = bench._assemble(configs, "TPU v5 lite", 197e12, "table", "bfloat16",
                           h2d_mbps=8000.0)
    assert "link_degraded" not in res2 and res2["value"] == 0.01
    assert res2["unit"] == "MFU"


def test_baselines_match_baseline_md_rows():
    # the ratios the suite reports are anchored to these exact numbers
    assert bench.BASELINES["resnet50"] == 81.69
    assert bench.BASELINES["resnet50_infer_fp32"] == 217.69
    assert bench.BASELINES["googlenet_infer"] == 600.94
    assert abs(bench.BASELINES["lstm_big"] - 256 / 1.655) < 1e-9
