"""paddle_tpu.fleet acceptance suite: continuous batching + the
replicated serving fleet. All CPU + deterministic fault injection.

The acceptance contracts:

  * coalesced-batch results are BIT-identical to the same requests run
    pad-alone through Predictor.run, with compiles_since_warmup == 0
    after warmup (the batching scheduler only ever fills precompiled
    buckets);
  * per-request deadlines/spans/validation survive coalescing (an
    expired group member is dropped unexecuted; each member's journal
    timeline carries its own span);
  * kill-one-replica under load: zero accepted-then-dropped requests —
    never-dispatched requests reroute transparently, dispatched ones
    surface ReplicaDied exactly once; fleet health degrades and
    recovers; the flight recorder captures the kill with an in-flight
    span;
  * rolling reload canaries one replica and rolls back fleet-wide on
    failure with zero dropped in-flight requests;
  * the aggregated /metrics merges every replica's series under a
    `replica` label and stays naming-convention clean;
  * batched int8-KV decode through the scheduler equals sequential
    decode;
  * tools/fleet_drill.py passes its own contracts (exit 0).
"""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu import serving, telemetry
from paddle_tpu.fleet import BatchPolicy, FleetRouter, NoReplicaAvailable
from paddle_tpu.fleet import batching as fbatch
from paddle_tpu.serving import (CircuitOpen, DeadlineExceeded,
                                PredictorServer, ReloadFailed, ReplicaDied,
                                ServerClosed, ServerOverloaded)
from paddle_tpu.telemetry.journal import RunJournal
from paddle_tpu.testing import faults


def _feed(n, seed=0):
    rng = np.random.RandomState(seed)
    return {"image": rng.randn(n, 784).astype(np.float32),
            "label": rng.randint(0, 10, (n, 1)).astype(np.int64)}


def _single(feed, i):
    return {k: np.asarray(v)[i:i + 1] for k, v in feed.items()}


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    from paddle_tpu.models import mnist

    d = str(tmp_path_factory.mktemp("fleet") / "model")
    prog = pt.build(mnist.mlp)
    feed8 = _feed(8)
    params, state = prog.init(jax.random.PRNGKey(0), **feed8)
    pio.save_inference_model(d, prog, jax.tree.map(np.asarray, params),
                             state, feed8, batch_buckets=[4, 8])
    return {"dir": d, "prog": prog, "params": params, "state": state,
            "feed8": feed8}


@pytest.fixture(scope="module")
def pred(artifact):
    return pio.load_inference_model(artifact["dir"])


@pytest.fixture()
def fresh_journal():
    old = telemetry.set_journal(RunJournal())
    try:
        yield telemetry.get_journal()
    finally:
        telemetry.set_journal(old)


def _export_variant(artifact, tmp_path, name, mutate):
    params = jax.tree.map(np.asarray, artifact["params"])
    params = mutate(params)
    d = str(tmp_path / name)
    pio.save_inference_model(d, artifact["prog"], params, artifact["state"],
                             artifact["feed8"], batch_buckets=[4, 8])
    return d


# -- batching planner units ---------------------------------------------------


class _FakeReq:
    def __init__(self, n, feed):
        self.n = n
        self.feed = feed


def test_pick_bucket_and_row_spans():
    assert fbatch.pick_bucket(1, [4, 8]) == 4
    assert fbatch.pick_bucket(5, [4, 8]) == 8
    assert fbatch.pick_bucket(8, [4, 8]) == 8
    with pytest.raises(ValueError, match="exceed the largest"):
        fbatch.pick_bucket(9, [4, 8])
    group = [_FakeReq(2, None), _FakeReq(1, None), _FakeReq(3, None)]
    assert fbatch.row_spans(group) == [(0, 2), (2, 1), (3, 3)]


def test_merge_feeds_and_nonbatched_key():
    f1 = {"x": np.arange(4, dtype=np.float32).reshape(2, 2),
          "k": np.float32(7.0)}
    f2 = {"x": np.arange(4, 6, dtype=np.float32).reshape(1, 2),
          "k": np.float32(7.0)}
    f3 = {"x": np.zeros((1, 2), np.float32), "k": np.float32(8.0)}
    names, batched = ["k", "x"], {"x"}
    assert fbatch.nonbatched_key(f1, names, batched) == \
        fbatch.nonbatched_key(f2, names, batched)
    assert fbatch.nonbatched_key(f1, names, batched) != \
        fbatch.nonbatched_key(f3, names, batched)
    merged = fbatch.merge_feeds([_FakeReq(2, f1), _FakeReq(1, f2)],
                                names, batched, bucket=4)
    assert merged["x"].shape == (4, 2)
    np.testing.assert_array_equal(merged["x"][:3],
                                  np.concatenate([f1["x"], f2["x"]]))
    np.testing.assert_array_equal(merged["x"][3:], 0)
    assert merged["k"] == np.float32(7.0)


def test_slice_rows_identity_and_slicing():
    out = {"y": np.arange(8), "scalar": np.float32(1.0)}
    assert fbatch.slice_rows(out, 0, 8, 8) is out       # whole bucket
    part = fbatch.slice_rows(out, 2, 3, 8)
    np.testing.assert_array_equal(part["y"], [2, 3, 4])
    assert part["scalar"] == np.float32(1.0)            # non-bucket leaf whole


# -- continuous batching through PredictorServer ------------------------------


def test_coalesced_bit_identical_to_pad_alone_zero_compiles(
        pred, fresh_journal):
    """THE batching acceptance pin: singles coalesce into one bucket
    dispatch, every caller's sliced rows are BIT-identical to the same
    request run pad-alone through Predictor.run into the bucket the
    scheduler dispatched (same executable — the scheduler only turns
    pad rows into real rows; each request's dispatched bucket is read
    back from its span's journal event), and the AOT compile count
    never moves."""
    feed8 = _feed(8, seed=3)

    def pad_alone(f, n, b):
        padded = {k: np.concatenate(
            [np.asarray(v),
             np.zeros((b - n,) + np.asarray(v).shape[1:],
                      np.asarray(v).dtype)]) for k, v in f.items()}
        return np.asarray(pred.run(padded)["logits"])[:n]

    def dispatched_bucket(p):
        ev = [e for e in fresh_journal.recent(span=p.span)
              if e["kind"] == "serving.dispatch"]
        assert len(ev) == 1
        return ev[0]["bucket"]

    srv = PredictorServer(pred, workers=1, queue_size=32,
                          batch_policy=BatchPolicy(max_wait_ms=50.0))
    try:
        before = pio.aot_compile_count()
        pends = [srv.submit(_single(feed8, i)) for i in range(6)]
        pends.append(srv.submit({k: np.asarray(v)[:2]
                                 for k, v in feed8.items()}))
        outs = [np.asarray(p.result(timeout=60)["logits"]) for p in pends]
        for i in range(6):
            assert outs[i].shape == (1, 10)
            assert outs[i].tobytes() == pad_alone(
                _single(feed8, i), 1, dispatched_bucket(pends[i])).tobytes()
        assert outs[6].tobytes() == pad_alone(
            {k: np.asarray(v)[:2] for k, v in feed8.items()}, 2,
            dispatched_bucket(pends[6])).tobytes()
        rep = srv.report()
        assert pio.aot_compile_count() == before
        assert rep["compiles_since_warmup"] == 0
        assert rep["coalesced_batches"] >= 1
        assert rep["coalesced_requests"] >= 4
        assert rep["completed"] == 7
    finally:
        srv.close(drain=True, timeout=30)


def test_coalesced_full_bucket_request_still_bit_identical(pred):
    """A request that IS a whole bucket passes through untouched (the
    PR-5 bit-identity contract survives batch_policy)."""
    feed8 = _feed(8, seed=4)
    golden = np.asarray(pred.run(feed8)["logits"])
    srv = PredictorServer(pred, workers=1, queue_size=8,
                          batch_policy=BatchPolicy(max_wait_ms=1.0))
    try:
        got = np.asarray(srv.run(feed8, timeout=60)["logits"])
        assert got.tobytes() == golden.tobytes()
    finally:
        srv.close(drain=True, timeout=30)


def test_coalesce_preserves_deadlines_and_spans(pred, fresh_journal):
    """A group member whose deadline expired while queued is dropped
    WITHOUT executing; each member's journal timeline carries its own
    span with submit→dispatch→complete and the coalesced row map."""
    release = threading.Event()
    hang = faults.hanging_predictor(pred, release, hang_calls=1)
    srv = PredictorServer(hang, workers=1, queue_size=16, warmup=False,
                          watchdog_timeout=30.0,
                          batch_policy=BatchPolicy(max_wait_ms=1.0))
    try:
        feed8 = _feed(8)
        blocker = srv.submit(feed8)          # wedges the lone worker
        time.sleep(0.05)
        expiring = srv.submit(_single(feed8, 0), deadline=0.01)
        live = [srv.submit(_single(feed8, i)) for i in range(1, 4)]
        time.sleep(0.1)                      # the deadline passes queued
        release.set()
        blocker.result(timeout=60)
        with pytest.raises(DeadlineExceeded):
            expiring.result(timeout=60)
        for p in live:
            assert np.asarray(p.result(timeout=60)["logits"]).shape == (1, 10)
        assert srv.metrics.snapshot()["timeouts"] == 1
        # span timelines: every live request has its own full lifecycle
        for p in live:
            kinds = [e["kind"] for e in fresh_journal.recent(span=p.span)]
            assert kinds[0] == "serving.submit"
            assert "serving.dispatch" in kinds
            assert kinds[-1] == "serving.complete"
        disp = [e for e in fresh_journal.recent(kind="serving.dispatch")
                if e.get("coalesced")]
        assert disp and all("row" in e for e in disp)
        # the expired member never dispatched
        assert not [e for e in fresh_journal.recent(kind="serving.dispatch")
                    if e.get("span") == expiring.span]
    finally:
        release.set()
        srv.close(drain=False, timeout=5)


def test_coalesced_error_fails_every_member_typed(pred):
    flaky = faults.failing_predictor(pred, fail_calls=1)
    srv = PredictorServer(flaky, workers=1, queue_size=16, warmup=False,
                          batch_policy=BatchPolicy(max_wait_ms=50.0))
    try:
        feed8 = _feed(8)
        pends = [srv.submit(_single(feed8, i)) for i in range(3)]
        outcomes = []
        for p in pends:
            try:
                p.result(timeout=60)
                outcomes.append("ok")
            except RuntimeError:
                outcomes.append("err")
        # the injected failure hits ONE dispatch: either all three
        # coalesced into it (all err) or the first dispatch failed and
        # the rest succeeded — never a hang, never an untyped outcome
        assert "err" in outcomes
        m = srv.metrics.snapshot()
        assert m["errors"] == outcomes.count("err")
    finally:
        srv.close(drain=False, timeout=5)


# -- fleet router -------------------------------------------------------------


def test_router_routes_around_dead_replica_and_health(pred):
    servers = {"r0": PredictorServer(pred, workers=1, queue_size=8),
               "r1": PredictorServer(pred.clone(), workers=1, queue_size=8)}
    router = FleetRouter(servers)
    try:
        feed8 = _feed(8)
        assert router.health()["state"] == "ready"
        faults.kill_server(router.replica("r0"))
        h = router.health()
        assert h["state"] == "degraded" and h["ready"]
        for _ in range(3):   # routing skips the dead replica
            out = router.run(feed8, timeout=60)
            assert np.asarray(out["logits"]).shape == (8, 10)
        assert router.report()["routed"]["r1"] >= 3
        # adopted fleet: replace() needs an explicit server
        with pytest.raises(ValueError, match="explicit server"):
            router.replace("r0")
        router.replace("r0", PredictorServer(pred.clone(), workers=1,
                                             queue_size=8))
        assert router.health()["state"] == "ready"
    finally:
        router.close(drain=False, timeout=10)


def test_router_front_door_shed_overload_and_deadline(pred):
    release = threading.Event()
    hang = faults.hanging_predictor(pred, release, hang_calls=2)
    servers = [PredictorServer(hang, workers=1, queue_size=1, warmup=False,
                               watchdog_timeout=30.0),
               PredictorServer(hang.clone(), workers=1, queue_size=1,
                               warmup=False, watchdog_timeout=30.0)]
    router = FleetRouter(servers, default_deadline=30.0)
    try:
        feed8 = _feed(8)
        pends = []
        shed_err = None
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and shed_err is None:
            try:
                pends.append(router.submit(feed8))  # wedges, then fills
            except ServerOverloaded as err:
                shed_err = err
        assert shed_err is not None, "fleet never shed under saturation"
        assert shed_err.capacity == 2   # summed front-door capacities
        rep = router.report()
        assert rep["shed"] >= 1
        assert rep["submitted"] == len(pends)   # shed ≠ accepted intake
        release.set()
        for p in pends:
            p.result(timeout=60)
    finally:
        release.set()
        router.close(drain=False, timeout=10)


def test_kill_drill_zero_dropped_at_saturation(artifact, fresh_journal):
    """THE kill acceptance pin, at ~3x saturation: kill one replica
    mid-load → zero accepted-then-dropped (no ServerClosed surfaces),
    never-dispatched requests reroute, dispatched ones surface
    ReplicaDied exactly once, health degrades and recovers, and the
    flight recorder holds the kill with an in-flight span."""
    router = FleetRouter.spawn(artifact["dir"], replicas=3, workers=1,
                               queue_size=16,
                               batch_policy=BatchPolicy(max_wait_ms=2.0))
    try:
        feed8 = artifact["feed8"]
        # measure service rate, then offer 3x
        for _ in range(2):
            router.run(feed8, timeout=60)
        t0 = time.perf_counter()
        for _ in range(6):
            router.run(feed8, timeout=60)
        svc = (time.perf_counter() - t0) / 6
        interval = svc / 3.0 / 3          # 3 workers at 3x saturation
        pends, shed = [], 0
        states_during = []
        for i in range(60):
            try:
                pends.append(router.submit(_single(feed8, i % 8)))
            except (ServerOverloaded, CircuitOpen, NoReplicaAvailable):
                shed += 1
            if i == 20:
                faults.kill_server(router.replica("r1"))
                states_during.append(router.health()["state"])
            time.sleep(interval)
        ok, died, dropped = 0, [], []
        for p in pends:
            try:
                p.result(timeout=60)
                ok += 1
            except ReplicaDied:
                died.append(p)
            except BaseException as e:
                dropped.append(e)
        assert not dropped, f"accepted requests dropped: {dropped[:3]}"
        assert ok + len(died) == len(pends)
        assert states_during == ["degraded"]
        router.replace("r1")
        assert router.health()["state"] == "ready"
        assert router.run(feed8, timeout=60) is not None
        # the flight recorder captured the kill; if requests were
        # in-flight, the dump's span belongs to one of them
        dumps = [d for d in telemetry.get_recorder().dumps
                 if "replica_killed" in d]
        assert dumps
        with open(os.path.join(dumps[-1], "flight.json")) as f:
            meta = json.load(f)
        assert meta["trigger"] == "replica_killed"
        if died:
            assert meta["span"] in {p.span for p in died}
        rep = router.report()
        assert rep["rerouted"] >= 0 and rep["replicas_replaced"] == 1
    finally:
        router.close(drain=False, timeout=10)


def test_rolling_reload_fans_out_with_zero_drops(artifact, tmp_path):
    d2 = _export_variant(artifact, tmp_path, "v2",
                         lambda p: jax.tree.map(lambda v: v * 0.5, p))
    router = FleetRouter.spawn(artifact["dir"], replicas=2, workers=1,
                               queue_size=16,
                               golden_feed=artifact["feed8"])
    errors, results = [], []
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            try:
                results.append(router.run(artifact["feed8"], timeout=60))
            except ServerOverloaded:
                pass
            except BaseException as e:
                errors.append(e)
                return

    t = threading.Thread(target=pump)
    t.start()
    try:
        time.sleep(0.05)
        gens = router.reload(d2)
        assert gens == {"r0": 2, "r1": 2}
        assert router.dirname == d2
        stop.set()
        t.join(timeout=60)
        assert not errors                # zero dropped in-flight
        assert len(results) >= 1
        assert router.report()["reloads"] == 1
        # a sibling's off-path reload must not read as a request-path
        # recompile: the router re-pins the whole fleet (the AOT
        # counter is process-wide)
        for n in router.replica_names:
            assert router.replica(n).report()["compiles_since_warmup"] == 0
    finally:
        stop.set()
        t.join(timeout=10)
        router.close(drain=True, timeout=30)


def test_rolling_reload_failed_canary_rolls_back_fleet_wide(
        artifact, tmp_path):
    d_nan = _export_variant(
        artifact, tmp_path, "vnan",
        lambda p: jax.tree.map(lambda v: np.full_like(v, np.nan), p))
    router = FleetRouter.spawn(artifact["dir"], replicas=2, workers=1,
                               queue_size=16,
                               golden_feed=artifact["feed8"])
    inflight = []
    try:
        inflight = [router.submit(artifact["feed8"]) for _ in range(3)]
        with pytest.raises(ReloadFailed, match="non-finite"):
            router.reload(d_nan)
        # fleet untouched: every replica still generation 1, previous
        # artifact still on record, in-flight requests all complete
        assert all(router.replica(n).generation == 1
                   for n in router.replica_names)
        assert router.dirname == artifact["dir"]
        for p in inflight:
            p.result(timeout=60)
        assert router.report()["reload_failures"] == 1
    finally:
        router.close(drain=True, timeout=30)


def test_rolling_reload_mid_rollout_failure_rolls_back(artifact, tmp_path,
                                                       pred):
    """Canary passes, a LATER replica rejects → every already-swapped
    replica is rolled back to the previous artifact."""
    d2 = _export_variant(artifact, tmp_path, "v2mid",
                         lambda p: jax.tree.map(lambda v: v * 0.5, p))
    golden_v1 = np.asarray(pred.run(artifact["feed8"])["logits"])
    servers = {
        "r0": PredictorServer(pred.clone(), workers=1, queue_size=8,
                              golden_feed=artifact["feed8"]),
        # r1 vetoes every candidate: the mid-rollout failure
        "r1": PredictorServer(pred.clone(), workers=1, queue_size=8,
                              golden_feed=artifact["feed8"],
                              canary_check=lambda out: False),
    }
    router = FleetRouter(servers, dirname=artifact["dir"])
    try:
        with pytest.raises(ReloadFailed, match="rolled back"):
            router.reload(d2)
        # r0 swapped to v2 then back to v1: generation 3, v1 outputs
        assert router.replica("r0").generation == 3
        got = np.asarray(
            router.replica("r0").run(artifact["feed8"],
                                     timeout=60)["logits"])
        assert got.tobytes() == golden_v1.tobytes()
        assert router.dirname == artifact["dir"]
        assert router.report()["reload_rollbacks"] == 1
    finally:
        router.close(drain=True, timeout=30)


# -- aggregated telemetry -----------------------------------------------------


def test_fleet_metrics_merge_replica_labels_and_validate_clean(pred):
    servers = {"a": PredictorServer(pred, workers=1, queue_size=8),
               "b": PredictorServer(pred.clone(), workers=1, queue_size=8)}
    router = FleetRouter(servers)
    try:
        feed8 = _feed(8)
        for _ in range(3):
            router.run(feed8, timeout=60)
        fams = router.metrics_families()
        assert telemetry.validate_families(fams) == []
        by_name = {f.name: f for f in fams}
        sub = by_name["paddle_tpu_serving_submitted_total"]
        assert {s[0]["replica"] for s in sub.samples} == {"a", "b"}
        assert sum(v for _, v in sub.samples) == 3
        routed = by_name["paddle_tpu_fleet_routed_total"]
        assert all(s[0]["replica"] in ("a", "b", "router")
                   for s in routed.samples)
        # the endpoint serves the merged export, text AND json
        ts = router.serve_metrics()
        text = urllib.request.urlopen(ts.url + "/metrics").read().decode()
        assert 'replica="a"' in text and 'replica="b"' in text
        assert "paddle_tpu_fleet_submitted_total" in text
        js = json.loads(urllib.request.urlopen(
            ts.url + "/metrics?format=json").read().decode())
        assert "paddle_tpu_fleet_routed_total" in js
        health = json.loads(urllib.request.urlopen(
            ts.url + "/healthz").read().decode())
        assert health["state"] == "ready"
        assert health["replicas_ready"] == 2
    finally:
        router.close(drain=True, timeout=30)


def test_merge_exports_unit():
    from paddle_tpu.telemetry.registry import counter_family, merge_exports

    fams = merge_exports(
        {"r0": [counter_family("paddle_tpu_x_y_total", "h",
                               [({"inst": "0"}, 1)])],
         "r1": [counter_family("paddle_tpu_x_y_total", "h",
                               [({"inst": "0"}, 2)])]})
    assert len(fams) == 1
    assert sorted((s[0]["replica"], s[1]) for s in fams[0].samples) == \
        [("r0", 1), ("r1", 2)]
    # pre-stamped labels survive (nested merges don't re-stamp)
    fams = merge_exports(
        {"outer": [counter_family("paddle_tpu_x_y_total", "h",
                                  [({"replica": "inner"}, 5)])]})
    assert fams[0].samples[0][0]["replica"] == "inner"
    with pytest.raises(ValueError, match="label"):
        merge_exports({}, label="BAD LABEL")


# -- decode workload ----------------------------------------------------------


def test_batched_int8_kv_decode_equals_sequential(tmp_path):
    """ROADMAP item (c): incremental decoding with the int8 KV cache
    as a SERVED workload — N single-prompt requests coalesced by the
    batching scheduler emit exactly the tokens each prompt gets from a
    sequential pad-alone decode, with zero request-path compiles."""
    from paddle_tpu.fleet import decode as fdecode
    from paddle_tpu.models import gpt

    cfg = gpt.base_config(vocab_size=16, max_len=32, d_model=32,
                          d_inner=64, num_heads=4, num_layers=2,
                          use_flash=False, fused_ce=False,
                          kv_cache_dtype="int8")
    d = str(tmp_path / "decoder")
    rng = np.random.RandomState(0)
    prompts = rng.randint(3, 16, (4, 8)).astype(np.int32)
    fdecode.export_decoder(d, cfg, max_new_tokens=6,
                           example_prompt=prompts, batch_buckets=[1, 4])
    pred = pio.load_inference_model(d)
    sequential = [np.asarray(pred.run({"prompt_ids": prompts[i:i + 1]})
                             ["ids"]) for i in range(4)]
    srv = fdecode.decode_server(d, max_wait_ms=50.0, workers=1)
    try:
        pends = [srv.submit({"prompt_ids": prompts[i:i + 1]})
                 for i in range(4)]
        outs = [np.asarray(p.result(timeout=120)["ids"]) for p in pends]
        for i in range(4):
            np.testing.assert_array_equal(outs[i], sequential[i])
        rep = srv.report()
        assert rep["compiles_since_warmup"] == 0
        assert rep["coalesced_requests"] >= 2
    finally:
        srv.close(drain=True, timeout=60)


# -- the drill tool (tier-1) --------------------------------------------------


def test_fleet_drill_tool_passes():
    from tools import fleet_drill

    assert fleet_drill.main(["--replicas", "2", "--requests", "45"]) == 0


def test_fleet_drill_tool_rejects_unknown_drill():
    from tools import fleet_drill

    assert fleet_drill.main(["--drills", "nope"]) == 3
