// Async parameter server — the TPU-native re-expression of the
// reference's async pserver runtime (listen_and_serv_op.cc:217
// RunAsyncLoop: per-grad optimize block applied on arrival, no
// trainer barriers) including DC-ASGD delay compensation
// (distribute_transpiler.py:1571 _append_dc_asgd_ops: the adjusted
// gradient g' = g + lambda*g*g*(w - w_bak[trainer]), with w_bak
// captured per trainer at param-pull time).
//
// Design notes (vs the reference): the reference splits the ProgramDesc
// into trainer/pserver programs and runs gRPC-transported optimize
// blocks inside the C++ interpreter. Here the dense/sparse update rules
// ARE the server (SGD / Adagrad / row-wise sparse), the transport is
// the same line-framed TCP protocol the C++ master uses, and trainers
// are JAX processes that jit only the gradient computation — the
// optimizer state lives host-side on the server, which is exactly the
// pserver placement in the reference (optimizer ops run on the pserver,
// distribute_transpiler.py:592-837). Sync SPMD training remains the
// first-class path (parallel/); this server exists for the async-SGD
// capability row.
//
// Build: g++ -O2 -std=c++17 -pthread pserver.cc -o pserver_server
// Run:   pserver_server <port> <lr> <sgd|adagrad> <dc_asgd 0|1> [lambda]
//                       [snapshot_path]
//        port 0 picks a free port; prints "PORT <n>" on stdout. With a
//        snapshot_path, state is recovered from it at startup (the
//        go/pserver/service.go:346 shard-checkpoint capability).
//
// Protocol (one request line; binary payloads length-prefixed):
//   INIT <name> <len>\n<f32 bytes>  -> OK NEW | OK EXISTS  (first writer wins)
//   PULL <trainer> <name>           -> OK <len>\n<f32 bytes>
//   PUSH <trainer> <name> <len>\n<f32 bytes>              -> OK <version>
//   PUSHQ <trainer> <name> <n> <scale>\n<i8 bytes>        -> OK <version>
//       (int8-quantized gradient: g[i] = q[i]*scale/127 — 4x less wire
//        than PUSH; quantized-collective lineage, EQuARX-style)
//   PUSHQB <trainer> <name> <n> <bits> <block>\n<f32 scales><codes> -> OK <v>
//       (block-scaled quantized gradient: one f32 abs-max scale per
//        <block> elements, codes int8 or packed int4 nibble pairs when
//        <bits>=4 — 4-8x less wire than PUSH with outliers contained to
//        their own block; same codec as parallel/quantized_collectives.
//        <n> is the UNPADDED element count; scale/code lengths derive
//        from n/bits/block server-side)
//   PUSHROWS <trainer> <name> <nrows> <rowdim>\n<i32 ids><f32 vals> -> OK <v>
//   EXPORT <name>                   -> OK <vlen> <alen> <version>\n
//                                      <f32 value><f32 accum>
//       (full shard-migration state of one param: value + optimizer
//        accumulator + version; per-trainer DC-ASGD baks are staleness
//        references and do not migrate, same as SAVE)
//   IMPORT <name> <vlen> <alen> <version>\n<f32 value><f32 accum> -> OK
//       (absolute overwrite-or-create — the receive half of a pserver
//        shard split/merge. Idempotent by construction: importing the
//        same state twice is a no-op, so the client may safely retry
//        it across a connection loss, unlike PUSH)
//   DELETE <name>                   -> OK GONE | OK ABSENT (idempotent)
//       (the cleanup half of shard migration: the old owner drops its
//        copy AFTER routing switched, so orphaned shards neither leak
//        memory across resizes nor silently absorb pushes from
//        trainers that have not rebound yet — those now fail loudly
//        with ERR unknown param)
//   SAVE                            -> OK | ERR (atomic snapshot to path)
//   STATUS                          -> OK params=N pushes=M
//   QUIT                            -> closes the connection
//
// Optional trace field: a client may append " trace=<id>" (no
// whitespace in <id>) to a PULL/PUSH/PUSHQ/PUSHQB/PUSHROWS header line. The
// field rides AFTER the positionally-parsed tokens, so an old server's
// sscanf ignores it (and an old client simply never sends it); this
// server echoes it at the end of the OK reply line ("OK <v>
// trace=<id>"), which old clients in turn ignore (they read reply
// fields positionally). The id is the telemetry span minted at the
// trainer's step, so a slow or lost exchange is attributable to a
// specific worker step against a specific pserver.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

enum class Opt { kSGD, kAdagrad };

struct Param {
  std::vector<float> value;
  std::vector<float> accum;                        // adagrad G += g^2
  std::map<int, std::vector<float>> bak;           // per-trainer w_bak
  int64_t version = 0;
};

class PServer {
 public:
  PServer(float lr, Opt opt, bool dc_asgd, float lambda,
          std::string snapshot_path)
      : lr_(lr), opt_(opt), dc_asgd_(dc_asgd), lambda_(lambda),
        snapshot_path_(std::move(snapshot_path)) {
    Recover();
  }

  std::string Init(const std::string& name, const std::string& bytes) {
    std::lock_guard<std::mutex> g(mu_);
    if (bytes.size() % sizeof(float) != 0)
      return "ERR payload not a multiple of sizeof(float)\n";
    auto it = params_.find(name);
    if (it != params_.end()) return "OK EXISTS\n";
    Param p;
    p.value.resize(bytes.size() / sizeof(float));
    memcpy(p.value.data(), bytes.data(), bytes.size());
    if (opt_ == Opt::kAdagrad) p.accum.assign(p.value.size(), 0.f);
    params_[name] = std::move(p);
    return "OK NEW\n";
  }

  std::string Pull(int trainer, const std::string& name, std::string* payload) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = params_.find(name);
    if (it == params_.end()) return "ERR unknown param " + name + "\n";
    Param& p = it->second;
    payload->assign(reinterpret_cast<const char*>(p.value.data()),
                    p.value.size() * sizeof(float));
    // DC-ASGD: the staleness reference point is the param value this
    // trainer last SAW — capture it at pull (ref_by_trainer_id analog).
    if (dc_asgd_) p.bak[trainer] = p.value;
    return "OK " + std::to_string(payload->size()) + "\n";
  }

  std::string Push(int trainer, const std::string& name,
                   const std::string& bytes) {
    std::lock_guard<std::mutex> g(mu_);
    size_t n = bytes.size() / sizeof(float);
    return ApplyDense(trainer, name, n,
                      reinterpret_cast<const float*>(bytes.data()));
  }

  // Quantized dense push: int8 payload + one f32 scale, dequantized
  // into a staging buffer and fed through the SAME update path as
  // Push — 4x less trainer→server traffic per gradient.
  std::string PushQuantized(int trainer, const std::string& name,
                            int64_t n, float scale,
                            const std::string& bytes) {
    if (n < 0 || bytes.size() != size_t(n)) return "ERR size mismatch\n";
    const int8_t* q = reinterpret_cast<const int8_t*>(bytes.data());
    std::vector<float> grad(static_cast<size_t>(n));
    const float inv = scale / 127.0f;
    for (int64_t i = 0; i < n; ++i) grad[i] = q[i] * inv;
    std::lock_guard<std::mutex> g(mu_);
    std::string resp = ApplyDense(trainer, name, size_t(n), grad.data());
    if (resp.rfind("OK", 0) == 0) ++qpushes_;
    return resp;
  }

  // Block-scaled quantized dense push: one f32 abs-max scale per
  // `block` elements, codes int8 or packed int4 nibble pairs (bias-8,
  // lo | hi<<4) — the PUSHQB wire verb, sharing its codec with the
  // trainer-side parallel/quantized_collectives encoder. Dequantized
  // into a staging buffer and fed through the SAME update path as
  // Push. A non-finite scale (the encoder poisons blocks that held
  // NaN/Inf) dequantizes its whole block to NaN and surfaces through
  // the update exactly like a NaN f32 push would.
  std::string PushQuantizedBlocks(int trainer, const std::string& name,
                                  int64_t n, int64_t bits, int64_t block,
                                  const std::string& scales_b,
                                  const std::string& codes_b) {
    if (n < 0 || block <= 0 || (bits != 8 && bits != 4) ||
        (bits == 4 && block % 2 != 0))
      return "ERR bad quant header\n";
    int64_t padded = ((n > 0 ? n : 1) + block - 1) / block * block;
    int64_t nblk = padded / block;
    int64_t codes_len = bits == 8 ? padded : padded / 2;
    if (scales_b.size() != size_t(nblk) * sizeof(float) ||
        codes_b.size() != size_t(codes_len))
      return "ERR size mismatch\n";
    const float* scales = reinterpret_cast<const float*>(scales_b.data());
    const float qmax = bits == 8 ? 127.0f : 7.0f;
    std::vector<float> grad(static_cast<size_t>(n));
    if (bits == 8) {
      const int8_t* q = reinterpret_cast<const int8_t*>(codes_b.data());
      for (int64_t i = 0; i < n; ++i)
        grad[i] = q[i] * (scales[i / block] / qmax);
    } else {
      const uint8_t* q = reinterpret_cast<const uint8_t*>(codes_b.data());
      for (int64_t i = 0; i < n; ++i) {
        uint8_t byte = q[i >> 1];
        int code = int((i & 1) ? (byte >> 4) & 0xF : byte & 0xF) - 8;
        grad[i] = code * (scales[i / block] / qmax);
      }
    }
    std::lock_guard<std::mutex> g(mu_);
    std::string resp = ApplyDense(trainer, name, size_t(n), grad.data());
    if (resp.rfind("OK", 0) == 0) ++qpushes_;
    return resp;
  }

  // Sparse rows (distributed-lookup-table update path: pserver-side
  // row-wise optimize, distribute_transpiler.py:1100-1339). Param is
  // [total_rows, rowdim] row-major; ids index rows. DC-ASGD is a dense
  // concept in the reference and is skipped for sparse pushes there too.
  std::string PushRows(const std::string& name, int64_t nrows, int64_t rowdim,
                       const std::string& ids_b, const std::string& vals_b) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = params_.find(name);
    if (it == params_.end()) return "ERR unknown param " + name + "\n";
    Param& p = it->second;
    if (nrows < 0 || rowdim <= 0) return "ERR bad nrows/rowdim\n";
    if (ids_b.size() != size_t(nrows) * sizeof(int32_t) ||
        vals_b.size() != size_t(nrows) * rowdim * sizeof(float))
      return "ERR size mismatch\n";
    const int32_t* ids = reinterpret_cast<const int32_t*>(ids_b.data());
    const float* vals = reinterpret_cast<const float*>(vals_b.data());
    int64_t total_rows = int64_t(p.value.size()) / rowdim;
    // validate every id BEFORE touching the param: a mid-loop ERR would
    // leave a half-applied update the client will retry (double-apply)
    for (int64_t r = 0; r < nrows; ++r)
      if (ids[r] < 0 || ids[r] >= total_rows) return "ERR row id out of range\n";
    for (int64_t r = 0; r < nrows; ++r)
      for (int64_t j = 0; j < rowdim; ++j)
        ApplyOne(&p, size_t(ids[r]) * rowdim + j, vals[r * rowdim + j]);
    ++p.version;
    ++pushes_;
    return "OK " + std::to_string(p.version) + "\n";
  }

  // Shard migration (the go/pserver slice/merge analog re-expressed as
  // a verb pair): EXPORT hands a param's full server-side state to the
  // coordinator, IMPORT installs it absolutely on the new owner.
  std::string Export(const std::string& name, std::string* payload) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = params_.find(name);
    if (it == params_.end()) return "ERR unknown param " + name + "\n";
    const Param& p = it->second;
    payload->assign(reinterpret_cast<const char*>(p.value.data()),
                    p.value.size() * sizeof(float));
    payload->append(reinterpret_cast<const char*>(p.accum.data()),
                    p.accum.size() * sizeof(float));
    return "OK " + std::to_string(p.value.size()) + " " +
           std::to_string(p.accum.size()) + " " +
           std::to_string(p.version) + "\n";
  }

  std::string Import(const std::string& name, int64_t vlen, int64_t alen,
                     int64_t version, const std::string& value_bytes,
                     const std::string& accum_bytes) {
    if (vlen < 0 || alen < 0 ||
        value_bytes.size() != size_t(vlen) * sizeof(float) ||
        accum_bytes.size() != size_t(alen) * sizeof(float))
      return "ERR size mismatch\n";
    Param p;
    p.value.resize(size_t(vlen));
    p.accum.resize(size_t(alen));
    memcpy(p.value.data(), value_bytes.data(), size_t(vlen) * sizeof(float));
    memcpy(p.accum.data(), accum_bytes.data(), size_t(alen) * sizeof(float));
    p.version = version;
    // re-establish the optimizer invariant Init() guarantees (same as
    // Recover): the exporter may run a different optimizer — ApplyOne
    // indexes accum unconditionally under adagrad
    if (opt_ == Opt::kAdagrad && p.accum.size() != p.value.size())
      p.accum.assign(p.value.size(), 0.f);
    if (opt_ == Opt::kSGD) p.accum.clear();
    std::lock_guard<std::mutex> g(mu_);
    // absolute overwrite (NOT first-writer-wins): a rejoining server may
    // hold a stale copy from before its shard moved away — migration
    // must install the authoritative state regardless
    params_[name] = std::move(p);
    return "OK IMPORTED\n";
  }

  std::string Delete(const std::string& name) {
    std::lock_guard<std::mutex> g(mu_);
    return params_.erase(name) ? "OK GONE\n" : "OK ABSENT\n";
  }

  std::string Status() {
    std::lock_guard<std::mutex> g(mu_);
    return "OK params=" + std::to_string(params_.size()) +
           " pushes=" + std::to_string(pushes_) +
           " qpushes=" + std::to_string(qpushes_) + "\n";
  }

  // Checkpoint of params + optimizer accumulators (pserver shard
  // checkpoint, go/pserver/service.go:346; per-trainer DC-ASGD baks are
  // staleness references, meaningless across a restart, so not saved).
  // State is COPIED under the lock and written outside it, so a slow
  // disk never stalls trainer push/pull traffic; the rename is atomic
  // and only happens after every write (incl. fclose flush) succeeded,
  // so a short write (disk full) cannot clobber the previous snapshot.
  std::string Save() {
    if (snapshot_path_.empty()) return "ERR no snapshot path configured\n";
    // serialize concurrent SAVEs BEFORE copying: if the copy happened
    // outside save_mu_, a later-copied (newer) snapshot could be
    // renamed first and then overwritten by an earlier stale copy — an
    // OK'd save would silently lose acknowledged durability
    std::lock_guard<std::mutex> sg(save_mu_);
    std::map<std::string, Param> copy;
    int64_t pushes;
    {
      std::lock_guard<std::mutex> g(mu_);
      for (auto& kv : params_) {
        Param p;
        p.value = kv.second.value;
        p.accum = kv.second.accum;
        p.version = kv.second.version;
        copy[kv.first] = std::move(p);  // baks intentionally dropped
      }
      pushes = pushes_;
    }
    std::string tmp = snapshot_path_ + ".tmp";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (!f) return "ERR cannot open snapshot tmp\n";
    bool ok = fprintf(f, "%zu %ld\n", copy.size(),
                      static_cast<long>(pushes)) > 0;
    for (auto& kv : copy) {
      if (!ok) break;
      const Param& p = kv.second;
      ok = fprintf(f, "%s %zu %zu %ld\n", kv.first.c_str(), p.value.size(),
                   p.accum.size(), static_cast<long>(p.version)) > 0 &&
           fwrite(p.value.data(), sizeof(float), p.value.size(), f) ==
               p.value.size() &&
           fwrite(p.accum.data(), sizeof(float), p.accum.size(), f) ==
               p.accum.size() &&
           fputc('\n', f) != EOF;
    }
    ok = (fclose(f) == 0) && ok;
    if (!ok) {
      remove(tmp.c_str());
      return "ERR snapshot write failed\n";
    }
    if (rename(tmp.c_str(), snapshot_path_.c_str()) != 0)
      return "ERR snapshot rename failed\n";
    return "OK\n";
  }

 private:
  // Shared dense-update core (callers hold mu_): DC-ASGD compensation +
  // the optimizer rule, for both exact and dequantized gradients.
  std::string ApplyDense(int trainer, const std::string& name, size_t n,
                         const float* grad) {
    auto it = params_.find(name);
    if (it == params_.end()) return "ERR unknown param " + name + "\n";
    Param& p = it->second;
    if (n != p.value.size()) return "ERR size mismatch\n";
    const float* bak = nullptr;
    if (dc_asgd_) {
      auto bit = p.bak.find(trainer);
      if (bit != p.bak.end() && bit->second.size() == n)
        bak = bit->second.data();
    }
    for (size_t i = 0; i < n; ++i) {
      float gi = grad[i];
      if (bak)  // g + lambda*g*g*(w - w_bak): 2nd-order delay compensation
        gi += lambda_ * gi * gi * (p.value[i] - bak[i]);
      ApplyOne(&p, i, gi);
    }
    ++p.version;
    ++pushes_;
    return "OK " + std::to_string(p.version) + "\n";
  }

  void Recover() {
    if (snapshot_path_.empty()) return;
    FILE* f = fopen(snapshot_path_.c_str(), "rb");
    if (!f) return;
    size_t n = 0;
    long pushes = 0;
    if (fscanf(f, "%zu %ld", &n, &pushes) != 2) {
      fclose(f);
      fprintf(stderr, "pserver: snapshot header unreadable, starting fresh\n");
      return;
    }
    fgetc(f);  // exactly the header newline
    // cap matches the protocol's 512MB payload bound: a corrupt size
    // field must not bad_alloc the server out of existence at startup
    const size_t kMaxLen = (512u << 20) / sizeof(float);
    // parse into a staging map: recovery is all-or-nothing, matching the
    // writer's atomicity contract — a half-loaded state (some params
    // recovered, pushes_ restored) would silently diverge
    std::unordered_map<std::string, Param> staged;
    bool complete = true;
    for (size_t i = 0; i < n; ++i) {
      char name[256];
      size_t vlen, alen;
      long version;
      // NOTE no trailing '\n' in the format: scanf's '\n' matches a RUN
      // of whitespace and would swallow leading payload bytes that
      // happen to be 0x09-0x0D/0x20, misaligning every later record
      if (fscanf(f, "%255s %zu %zu %ld", name, &vlen, &alen, &version) != 4 ||
          vlen > kMaxLen || alen > kMaxLen) {
        complete = false;
        break;
      }
      fgetc(f);  // exactly the header newline; payload starts next byte
      Param p;
      p.value.resize(vlen);
      p.accum.resize(alen);
      p.version = version;
      if (fread(p.value.data(), sizeof(float), vlen, f) != vlen ||
          (alen && fread(p.accum.data(), sizeof(float), alen, f) != alen)) {
        complete = false;
        break;
      }
      fgetc(f);  // trailing newline after the payload
      // re-establish the optimizer invariant Init() guarantees: the
      // snapshot may come from a server run with a different optimizer
      // (sgd: empty accum) — ApplyOne indexes accum unconditionally
      // under adagrad, so a size mismatch would be an OOB write
      if (opt_ == Opt::kAdagrad && p.accum.size() != p.value.size())
        p.accum.assign(p.value.size(), 0.f);
      if (opt_ == Opt::kSGD) p.accum.clear();
      staged[name] = std::move(p);
    }
    // full-consumption check (mirrors master.cc's snapshot loader): a
    // header whose param-count was corrupted to a SMALLER value parses
    // cleanly above but leaves tail params unread — that is a silent
    // partial load, which the all-or-nothing contract forbids
    bool trailing = complete && fgetc(f) != EOF;
    fclose(f);
    if (!complete || trailing) {
      if (trailing)
        fprintf(stderr,
                "pserver: snapshot has unconsumed bytes after %zu params "
                "(header count corrupted?), starting fresh\n", n);
      else
        fprintf(stderr,
                "pserver: snapshot truncated/corrupt (%zu of %zu params "
                "readable), starting fresh\n", staged.size(), n);
      return;
    }
    params_ = std::move(staged);
    pushes_ = pushes;
  }
  void ApplyOne(Param* p, size_t i, float g) {
    if (opt_ == Opt::kAdagrad) {
      p->accum[i] += g * g;
      p->value[i] -= lr_ * g / (std::sqrt(p->accum[i]) + 1e-6f);
    } else {
      p->value[i] -= lr_ * g;
    }
  }

  std::mutex mu_;
  std::mutex save_mu_;
  std::unordered_map<std::string, Param> params_;
  int64_t pushes_ = 0;
  int64_t qpushes_ = 0;  // subset of pushes_ that arrived quantized
  float lr_;
  Opt opt_;
  bool dc_asgd_;
  float lambda_;
  std::string snapshot_path_;
};

// -- line-framed socket IO (shared shape with master.cc) ---------------------

bool ReadLine(int fd, std::string* line) {
  line->clear();
  char c;
  while (true) {
    ssize_t r = recv(fd, &c, 1, 0);
    if (r <= 0) return false;
    if (c == '\n') return true;
    line->push_back(c);
    if (line->size() > 1 << 20) return false;
  }
}

bool ReadExact(int fd, char* buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = recv(fd, buf + got, n - got, 0);
    if (r <= 0) return false;
    got += r;
  }
  return true;
}

bool WriteAll(int fd, const char* buf, size_t n) {
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = send(fd, buf + sent, n - sent, MSG_NOSIGNAL);
    if (r <= 0) return false;
    sent += r;
  }
  return true;
}

bool ReadBody(int fd, size_t len, std::string* body) {
  if (len > (512u << 20)) return false;
  body->resize(len);
  return len == 0 || ReadExact(fd, &(*body)[0], len);
}

// Echo a request header's optional " trace=<id>" token at the end of an
// OK reply line (see the protocol note above). ERR replies are left
// untouched — their text is part of the error contract.
std::string WithTrace(std::string resp, const std::string& line) {
  size_t pos = line.rfind(" trace=");
  if (pos == std::string::npos || resp.rfind("OK", 0) != 0) return resp;
  std::string tok = line.substr(pos + 1);
  size_t sp = tok.find_first_of(" \t");
  if (sp != std::string::npos) tok.resize(sp);
  if (!resp.empty() && resp.back() == '\n') resp.pop_back();
  return resp + " " + tok + "\n";
}

void ServeClient(PServer* ps, int fd) {
  std::string line;
  while (ReadLine(fd, &line)) {
    std::string resp, payload;
    char name[256];
    long long a = 0, b = 0, c = 0, d = 0;
    if (sscanf(line.c_str(), "INIT %255s %lld", name, &a) == 2) {
      std::string body;
      if (!ReadBody(fd, a, &body)) break;
      resp = ps->Init(name, body);
    } else if (sscanf(line.c_str(), "PULL %lld %255s", &a, name) == 2) {
      resp = WithTrace(ps->Pull(int(a), name, &payload), line);
    } else if (sscanf(line.c_str(), "PUSH %lld %255s %lld", &a, name, &b) == 3) {
      // retry: at-most-once — replaying a gradient double-applies it
      std::string body;
      if (!ReadBody(fd, b, &body)) break;
      resp = WithTrace(ps->Push(int(a), name, body), line);
    } else if (sscanf(line.c_str(), "PUSHQB %lld %255s %lld %lld %lld",
                      &a, name, &b, &c, &d) == 5) {
      // retry: at-most-once
      // header sanity BEFORE sizing the reads: bits/block combinations
      // the codec cannot produce close the connection (body lengths
      // would be unknowable), and kMaxElems bounds keep every size_t
      // product below 2^64 (same overflow discipline as PUSHROWS)
      const long long kMaxElems = (512ll << 20) / int(sizeof(float));
      if (b < 0 || b > kMaxElems || d <= 0 || d > kMaxElems ||
          (c != 8 && c != 4) || (c == 4 && d % 2 != 0))
        break;
      long long padded = ((b > 0 ? b : 1) + d - 1) / d * d;
      std::string scales, codes;
      if (!ReadBody(fd, size_t(padded / d) * sizeof(float), &scales)) break;
      if (!ReadBody(fd, size_t(c == 8 ? padded : padded / 2), &codes)) break;
      resp = WithTrace(
          ps->PushQuantizedBlocks(int(a), name, b, c, d, scales, codes),
          line);
    } else if (float scale = 0.f;
               sscanf(line.c_str(), "PUSHQ %lld %255s %lld %f",
                      &a, name, &b, &scale) == 4) {
      // retry: at-most-once
      std::string body;
      if (b < 0 || !ReadBody(fd, size_t(b), &body)) break;
      resp = WithTrace(ps->PushQuantized(int(a), name, b, scale, body), line);
    } else if (sscanf(line.c_str(), "PUSHROWS %lld %255s %lld %lld",
                      &a, name, &b, &c) == 4) {
      // retry: at-most-once
      // reject before the size_t casts: a huge b or c would wrap the
      // b*c*sizeof(float) product past 2^64 to a tiny length, slipping
      // under the 512MB ReadBody cap while PushRows later indexes far
      // out of bounds. Bounding each factor by the payload cap keeps
      // every product below 2^64. b == 0 stays legal (PushRows permits
      // an empty sparse gradient and replies OK).
      const long long kMaxElems = (512ll << 20) / int(sizeof(float));
      if (b < 0 || c <= 0 || b > kMaxElems || c > kMaxElems) break;
      std::string ids, vals;
      if (!ReadBody(fd, size_t(b) * sizeof(int32_t), &ids)) break;
      if (!ReadBody(fd, size_t(b) * size_t(c) * sizeof(float), &vals)) break;
      resp = WithTrace(ps->PushRows(name, b, c, ids, vals), line);
    } else if (sscanf(line.c_str(), "EXPORT %255s", name) == 1) {
      resp = ps->Export(name, &payload);
    } else if (sscanf(line.c_str(), "DELETE %255s", name) == 1) {
      resp = ps->Delete(name);
    } else if (sscanf(line.c_str(), "IMPORT %255s %lld %lld %lld",
                      name, &a, &b, &c) == 4) {
      // same overflow discipline as PUSHROWS: bound each length by the
      // payload cap before the size_t arithmetic, and read value/accum
      // as SEPARATE bodies — each gets the full 512MB ReadBody budget,
      // so any param PUSH can carry (value <= cap) stays migratable
      // even with an equally large optimizer accumulator riding along
      const long long kMaxElems = (512ll << 20) / int(sizeof(float));
      if (a < 0 || b < 0 || a > kMaxElems || b > kMaxElems) break;
      std::string vbody, abody;
      if (!ReadBody(fd, size_t(a) * sizeof(float), &vbody)) break;
      if (!ReadBody(fd, size_t(b) * sizeof(float), &abody)) break;
      resp = ps->Import(name, a, b, c, vbody, abody);
    } else if (line == "SAVE") {
      resp = ps->Save();
    } else if (line == "STATUS") {
      resp = ps->Status();
    } else if (line == "QUIT") {
      break;
    } else if (line.rfind("INIT ", 0) == 0 || line.rfind("PUSH", 0) == 0 ||
               line.rfind("IMPORT ", 0) == 0) {
      // payload-carrying header that failed to parse (e.g. name >255
      // chars truncated by %255s): the payload length is unknowable, so
      // the stream is unrecoverable — close rather than desync into
      // interpreting raw floats as commands
      break;
    } else {
      resp = "ERR bad command\n";
    }
    if (!WriteAll(fd, resp.data(), resp.size())) break;
    if (!payload.empty() && !WriteAll(fd, payload.data(), payload.size()))
      break;
  }
  close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr,
            "usage: pserver_server <port> <lr> [sgd|adagrad] [dc_asgd 0|1] "
            "[lambda] [snapshot_path]\n");
    return 1;
  }
  int port = atoi(argv[1]);
  float lr = atof(argv[2]);
  Opt opt = (argc > 3 && std::string(argv[3]) == "adagrad") ? Opt::kAdagrad
                                                            : Opt::kSGD;
  bool dc = argc > 4 && atoi(argv[4]) != 0;
  float lambda = argc > 5 ? atof(argv[5]) : 1.0f;
  std::string snapshot = argc > 6 ? argv[6] : "";
  if (snapshot == "-") snapshot.clear();

  PServer ps(lr, opt, dc, lambda, snapshot);

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(port);
  if (bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  socklen_t alen = sizeof(addr);
  getsockname(srv, reinterpret_cast<sockaddr*>(&addr), &alen);
  listen(srv, 64);  // before PORT: clients connect the moment they see it
  printf("PORT %d\n", ntohs(addr.sin_port));
  fflush(stdout);

  while (true) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::thread(ServeClient, &ps, fd).detach();
  }
}
