"""Autoscaler acceptance suite: the pure policy core, the
complete-bucket guard, the control loop over fake readers/routers, and
the real ``FleetRouter.grow()`` / ``retire(drain=True)`` primitives.

The acceptance contracts:

  * every policy behavior — hysteresis windows, per-direction
    cooldowns, anti-flap, quorum floor, fail-static — is pinned WITHOUT
    a single sleep: the clock is an explicit ``now`` in ScaleSignals;
  * scale-up fires BOTH ways: a sustained trend read AND an alert
    transition (immediate, no sustain window on top);
  * stale telemetry pauses scaling AND resets sustain windows AND
    leaves the alert edge-detection baseline uncommitted (a collector
    failover never manufactures a firing edge);
  * the trend math only ever consumes complete downsample buckets;
  * ``retire(drain=True)`` completes every accepted in-flight request
    (at-most-once classification intact, zero dropped);
  * the agent's dead-children history stays bounded under churn with
    live pids never evicted;
  * per-origin flush jitter is deterministic and desynchronizes
    same-interval shippers.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

import jax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu.fleet import FleetRouter
from paddle_tpu.fleet.autoscaler import (AutoscalePolicy, Autoscaler,
                                         HttpCollectorReader,
                                         LocalCollectorReader, ScaleDecision,
                                         ScaleSignals, complete_buckets)
from paddle_tpu.telemetry.journal import RunJournal


# -- policy: every pin uses an explicit clock, no sleeps anywhere ------------


def _sig(now, replicas=2, **kw):
    return ScaleSignals(now=now, replicas=replicas, **kw)


def _policy(**kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("up_window_s", 2.0)
    kw.setdefault("down_window_s", 5.0)
    kw.setdefault("up_cooldown_s", 5.0)
    kw.setdefault("down_cooldown_s", 10.0)
    kw.setdefault("flap_guard_s", 10.0)
    return AutoscalePolicy(**kw)


class TestPolicyBand:
    def test_band_validation(self):
        with pytest.raises(ValueError):
            AutoscalePolicy(min_replicas=0, max_replicas=2)
        with pytest.raises(ValueError):
            AutoscalePolicy(min_replicas=3, max_replicas=2)

    def test_below_band_repair_ignores_cooldown(self):
        p = _policy(min_replicas=2, up_cooldown_s=100.0)
        # burn the up-cooldown with an alert-driven up at t=0
        d = p.decide(_sig(0.0, replicas=2, queue_per_replica=0.0,
                          alert_firing=True, alert_transition=True))
        assert d.action == "up"
        # a dead replica drops the fleet below min: repair fires even
        # inside the cooldown window
        d = p.decide(_sig(1.0, replicas=1, queue_per_replica=0.0))
        assert (d.action, d.reason, d.target) == ("up", "below-band", 2)

    def test_below_band_still_fail_static(self):
        p = _policy(min_replicas=2)
        d = p.decide(_sig(0.0, replicas=1, data_ok=False))
        assert (d.action, d.reason) == ("hold", "fail-static")


class TestPolicyScaleUp:
    def test_trend_must_sustain_up_window(self):
        p = _policy(up_queue_per_replica=2.0, up_window_s=2.0)
        assert p.decide(_sig(0.0, queue_per_replica=5.0)).reason == "steady"
        assert p.decide(_sig(1.0, queue_per_replica=5.0)).reason == "steady"
        d = p.decide(_sig(2.0, queue_per_replica=5.0))
        assert (d.action, d.reason, d.detail) == ("up", "trend-sustained",
                                                  "queue")
        assert d.target == 3

    def test_trend_gap_resets_sustain(self):
        p = _policy(up_queue_per_replica=2.0, up_window_s=2.0)
        p.decide(_sig(0.0, queue_per_replica=5.0))
        # one cold tick erases the partial sustain...
        p.decide(_sig(1.0, queue_per_replica=0.0))
        # ...so hot at t=2.5 has only been hot since t=2.5
        assert p.decide(_sig(2.5, queue_per_replica=5.0)).reason == "steady"
        assert p.decide(_sig(4.0, queue_per_replica=5.0)).reason == "steady"
        assert p.decide(_sig(4.5, queue_per_replica=5.0)).action == "up"

    def test_shed_rate_is_an_up_signal(self):
        p = _policy(up_shed_rate=1.0, up_window_s=0.0)
        d = p.decide(_sig(0.0, shed_rate=3.0))
        assert (d.action, d.detail) == ("up", "shed")

    def test_alert_transition_is_immediate(self):
        # the BOTH-trigger contract, second half: no sustain window on
        # top of a firing edge — the trend signals are stone cold here
        p = _policy(up_window_s=60.0)
        d = p.decide(_sig(0.0, queue_per_replica=0.0,
                          alert_firing=True, alert_transition=True))
        assert (d.action, d.reason, d.target) == ("up", "alert-transition", 3)

    def test_alert_transition_respects_at_max(self):
        p = _policy(max_replicas=2)
        d = p.decide(_sig(0.0, replicas=2, alert_firing=True,
                          alert_transition=True))
        assert (d.action, d.reason) == ("hold", "at-max")

    def test_up_cooldown_blocks_second_up(self):
        p = _policy(up_window_s=0.0, up_cooldown_s=5.0)
        assert p.decide(_sig(0.0, queue_per_replica=9.0)).action == "up"
        d = p.decide(_sig(3.0, queue_per_replica=9.0))
        assert (d.action, d.reason) == ("hold", "up-cooldown")
        # NB: the cooldown hold does not extend the cooldown
        assert p.decide(_sig(5.0, queue_per_replica=9.0)).action == "up"

    def test_up_resets_hot_window(self):
        # after an up the burst must re-prove itself: hot since the up,
        # not since the original onset
        p = _policy(up_window_s=2.0, up_cooldown_s=0.0)
        p.decide(_sig(0.0, queue_per_replica=9.0))
        assert p.decide(_sig(2.0, queue_per_replica=9.0)).action == "up"
        assert p.decide(_sig(3.0, queue_per_replica=9.0)).reason == "steady"
        assert p.decide(_sig(4.0, queue_per_replica=9.0)).reason == "steady"
        assert p.decide(_sig(5.0, queue_per_replica=9.0)).action == "up"

    def test_step_clamps_to_max(self):
        p = _policy(max_replicas=3, step=5, up_window_s=0.0)
        d = p.decide(_sig(0.0, replicas=2, queue_per_replica=9.0))
        assert (d.action, d.target) == ("up", 3)


class TestPolicyScaleDown:
    def _cold_run(self, p, t0=0.0, replicas=2):
        """Feed cold ticks until the down window has elapsed; return
        the decision at the window edge."""
        p.decide(_sig(t0, replicas=replicas, queue_per_replica=0.0))
        return p.decide(_sig(t0 + p.down_window_s, replicas=replicas,
                             queue_per_replica=0.0))

    def test_down_needs_sustained_cold(self):
        p = _policy(down_window_s=5.0)
        assert p.decide(_sig(0.0, queue_per_replica=0.0)).reason == "steady"
        assert p.decide(_sig(4.9, queue_per_replica=0.0)).reason == "steady"
        d = p.decide(_sig(5.0, queue_per_replica=0.0))
        assert (d.action, d.reason, d.target) == ("down", "trend-cold", 1)

    def test_hysteresis_gap_is_steady(self):
        # between down and up thresholds: neither hot nor cold
        p = _policy(up_queue_per_replica=2.0, down_queue_per_replica=0.5,
                    down_window_s=0.0)
        d = p.decide(_sig(0.0, queue_per_replica=1.0))
        assert (d.action, d.reason) == ("hold", "steady")

    def test_silence_is_not_coldness(self):
        # no trend signal present at all: never a down verdict
        p = _policy(down_window_s=0.0)
        assert p.decide(_sig(0.0)).reason == "steady"
        assert p.decide(_sig(100.0)).reason == "steady"

    def test_at_min_holds(self):
        p = _policy(min_replicas=1, down_window_s=0.0)
        d = p.decide(_sig(0.0, replicas=1, queue_per_replica=0.0))
        assert (d.action, d.reason) == ("hold", "at-min")

    def test_down_cooldown(self):
        p = _policy(down_window_s=0.0, down_cooldown_s=10.0,
                    flap_guard_s=0.0, max_replicas=4)
        d = p.decide(_sig(0.0, replicas=3, queue_per_replica=0.0))
        assert d.action == "down"
        d = p.decide(_sig(5.0, replicas=2, queue_per_replica=0.0))
        assert (d.action, d.reason) == ("hold", "down-cooldown")
        assert p.decide(_sig(10.0, replicas=2,
                             queue_per_replica=0.0)).action == "down"

    def test_anti_flap_runs_from_retire_completion(self):
        p = _policy(down_window_s=0.0, down_cooldown_s=0.0,
                    flap_guard_s=10.0, max_replicas=4)
        assert p.decide(_sig(0.0, replicas=3,
                             queue_per_replica=0.0)).action == "down"
        # the drain took 4 seconds: completion stamped at t=4, so the
        # flap guard holds until t=14 — not t=10
        p.note_retired(4.0)
        d = p.decide(_sig(12.0, replicas=2, queue_per_replica=0.0))
        assert (d.action, d.reason) == ("hold", "anti-flap")
        assert p.decide(_sig(14.0, replicas=2,
                             queue_per_replica=0.0)).action == "down"

    def test_quorum_floor_only_while_alert_fires(self):
        p = _policy(min_replicas=1, quorum=2, down_window_s=0.0,
                    down_cooldown_s=0.0, flap_guard_s=0.0)
        # trend cold but an alert still firing: never below quorum
        d = p.decide(_sig(0.0, replicas=2, queue_per_replica=0.0,
                          alert_firing=True))
        assert (d.action, d.reason) == ("hold", "quorum-floor")
        # alert resolved: the same cold trend may now shrink past it
        d = p.decide(_sig(1.0, replicas=2, queue_per_replica=0.0,
                          alert_firing=False))
        assert (d.action, d.target) == ("down", 1)

    def test_quorum_does_not_block_above_floor(self):
        p = _policy(min_replicas=1, quorum=2, max_replicas=4,
                    down_window_s=0.0, down_cooldown_s=0.0,
                    flap_guard_s=0.0)
        d = p.decide(_sig(0.0, replicas=4, queue_per_replica=0.0,
                          alert_firing=True))
        assert (d.action, d.target) == ("down", 3)


class TestPolicyFailStatic:
    def test_fail_static_holds_and_resets_windows(self):
        p = _policy(up_window_s=2.0)
        p.decide(_sig(0.0, queue_per_replica=9.0))
        d = p.decide(_sig(1.0, data_ok=False))
        assert (d.action, d.reason) == ("hold", "fail-static")
        # the gap erased the sustain: hot at t=2 (>= up_window past the
        # original onset) is NOT enough, it must re-sustain from t=2
        assert p.decide(_sig(2.0, queue_per_replica=9.0)).reason == "steady"
        assert p.decide(_sig(4.0, queue_per_replica=9.0)).action == "up"

    def test_fail_static_resets_cold_window_too(self):
        p = _policy(down_window_s=5.0)
        p.decide(_sig(0.0, queue_per_replica=0.0))
        p.decide(_sig(3.0, data_ok=False))
        assert p.decide(_sig(5.0, queue_per_replica=0.0)).reason == "steady"
        assert p.decide(_sig(10.0, queue_per_replica=0.0)).action == "down"


# -- complete_buckets --------------------------------------------------------


def test_complete_buckets_drops_trailing_partial():
    pts = [(0.0, 1.0), (0.5, 2.0), (1.0, 3.0)]
    # the bucket starting at 1.0 spans [1.0, 1.5) > to=1.2: partial
    assert complete_buckets(pts, step=0.5, to=1.2) == [(0.0, 1.0),
                                                      (0.5, 2.0)]
    # to=1.5 closes it
    assert complete_buckets(pts, step=0.5, to=1.5) == pts


def test_complete_buckets_raw_points_pass_through():
    pts = [(0.0, 1.0), (1.1, 2.0), (2.0, 3.0)]
    assert complete_buckets(pts, step=0.0, to=1.5) == [(0.0, 1.0),
                                                      (1.1, 2.0)]
    assert complete_buckets(pts, step=-1.0, to=5.0) == pts


def test_complete_buckets_empty():
    assert complete_buckets([], step=0.5, to=10.0) == []


# -- the control loop over fakes ---------------------------------------------


class _FakeRouter:
    def __init__(self, names=("r0",)):
        self.names = list(names)
        self.grown = []
        self.retired = []

    @property
    def replica_names(self):
        return list(self.names)

    def grow(self, name=None):
        name = name or f"r{len(self.names)}"
        self.names.append(name)
        self.grown.append(name)
        return name

    def retire(self, name, drain=True, timeout=None):
        self.names.remove(name)
        self.retired.append((name, drain))


class _FakeReader:
    """Scriptable collector: per-metric /query docs + /alerts snaps."""

    def __init__(self):
        self.queue_points = {}    # series key -> [(t, v), ...]
        self.shed_points = {}
        self.step = 0.5
        self.firing = []
        self.fail = False

    def query(self, metric, labels=None, start=0.0, end=None, step=0.0):
        if self.fail:
            raise ConnectionError("collector down")
        pts = self.queue_points if "queue" in metric else self.shed_points
        return {"metric": metric, "from": start, "to": end,
                "step": step if step else 0.0,
                "series": [{"key": k, "labels": {}, "points": list(v)}
                           for k, v in sorted(pts.items())]}

    def alerts(self):
        if self.fail:
            raise ConnectionError("collector down")
        return {"firing": list(self.firing)}


def _scaler(router, reader, policy=None, **kw):
    kw.setdefault("trend_window_s", 5.0)
    kw.setdefault("trend_step_s", 0.5)
    kw.setdefault("stale_after_s", 2.0)
    return Autoscaler(router, reader,
                      policy or _policy(up_window_s=0.0, up_cooldown_s=0.0),
                      **kw)


def _hot_queue(reader, now, per_replica=9.0, names=("r0", "r1")):
    """Fresh, complete hot buckets for every named series."""
    reader.queue_points = {
        n: [(now - 1.5, per_replica), (now - 1.0, per_replica),
            (now - 0.5, per_replica)]
        for n in names}


class TestAutoscalerLoop:
    def setup_method(self):
        from paddle_tpu import telemetry
        telemetry.set_journal(RunJournal())

    def test_trend_sustained_scale_up(self):
        # the BOTH-trigger contract, first half: a pure trend read —
        # no alert anywhere — grows the fleet once sustained
        router = _FakeRouter(["r0", "r1"])
        reader = _FakeReader()
        pol = _policy(up_queue_per_replica=2.0, up_window_s=1.0,
                      up_cooldown_s=0.0)
        with _scaler(router, reader, pol) as sc:
            _hot_queue(reader, 100.0)
            assert sc.tick(now=100.0).reason == "steady"
            _hot_queue(reader, 101.0)
            d = sc.tick(now=101.0)
            assert (d.action, d.reason) == ("up", "trend-sustained")
            assert router.grown == ["r2"]
            assert sc.counters()["scale_ups"] == 1

    def test_alert_transition_scale_up(self):
        # cold trend + a fresh firing edge: immediate up
        router = _FakeRouter(["r0", "r1"])
        reader = _FakeReader()
        with _scaler(router, reader, _policy(up_window_s=60.0),
                     alert_rules=["queue_hot"]) as sc:
            reader.queue_points = {"r0": [(99.5, 0.0)], "r1": [(99.5, 0.0)]}
            reader.firing = [{"rule": "queue_hot", "key": "r0"}]
            d = sc.tick(now=100.0)
            assert (d.action, d.reason) == ("up", "alert-transition")
            assert router.grown == ["r2"]
            # same alert still firing next tick: no new edge, no new up
            reader.queue_points = {n: [(100.5, 0.0)] for n in router.names}
            assert sc.tick(now=101.0).action == "hold"

    def test_alert_rules_filter(self):
        router = _FakeRouter(["r0", "r1"])
        reader = _FakeReader()
        with _scaler(router, reader, _policy(up_window_s=60.0),
                     alert_rules=["queue_hot"]) as sc:
            reader.queue_points = {"r0": [(99.5, 0.0)]}
            reader.firing = [{"rule": "unrelated_rule", "key": "x"}]
            d = sc.tick(now=100.0)
            assert d.action == "hold"
            assert router.grown == []

    def test_stale_data_is_fail_static(self):
        router = _FakeRouter(["r0", "r1"])
        reader = _FakeReader()
        with _scaler(router, reader, stale_after_s=2.0) as sc:
            # hot but ANCIENT points: freshest age 50s > stale_after
            reader.queue_points = {"r0": [(50.0, 9.0)], "r1": [(50.0, 9.0)]}
            d = sc.tick(now=100.0)
            assert (d.action, d.reason) == ("hold", "fail-static")
            assert sc.counters()["holds"]["fail-static"] == 1

    def test_reader_error_is_fail_static(self):
        router = _FakeRouter(["r0", "r1"])
        reader = _FakeReader()
        reader.fail = True
        with _scaler(router, reader) as sc:
            s = sc.signals(now=100.0)
            assert s.data_ok is False
            assert sc.tick(now=100.0).reason == "fail-static"

    def test_stale_tick_does_not_commit_alert_baseline(self):
        # the failover pin: while data is stale the alert view (empty,
        # replayed, whatever the promoting standby serves) must NOT
        # advance the edge baseline — and the still-firing alert after
        # recovery must NOT read as a fresh edge
        router = _FakeRouter(["r0", "r1"])
        reader = _FakeReader()
        with _scaler(router, reader, _policy(up_window_s=60.0)) as sc:
            reader.queue_points = {"r0": [(99.5, 0.0)], "r1": [(99.5, 0.0)]}
            reader.firing = [{"rule": "queue_hot", "key": "r0"}]
            assert sc.tick(now=100.0).action == "up"          # real edge
            # failover: stale data, alerts view briefly EMPTY
            reader.queue_points = {"r0": [(99.5, 0.0)]}
            reader.firing = []
            assert sc.tick(now=110.0).reason == "fail-static"
            # recovery: same alert still firing — not a new edge
            reader.queue_points = {n: [(119.5, 0.0)]
                                   for n in router.names}
            reader.firing = [{"rule": "queue_hot", "key": "r0"}]
            d = sc.tick(now=120.0)
            assert d.action == "hold"
            assert router.grown == ["r2"]   # exactly the one real up

    def test_partial_bucket_never_gates(self):
        # only a partial trailing bucket in the window: no verdict ⇒
        # the qpr signal is None and nothing scales on it
        router = _FakeRouter(["r0", "r1"])
        reader = _FakeReader()
        with _scaler(router, reader, trend_step_s=0.5) as sc:
            reader.queue_points = {"r0": [(99.8, 50.0)],
                                   "r1": [(99.8, 50.0)]}
            s = sc.signals(now=100.0)
            assert s.data_ok is True           # fresh, just no verdict
            assert s.queue_per_replica is None
            assert sc.tick(now=100.0).action == "hold"
            assert router.grown == []

    def test_queue_trend_sums_series_per_replica(self):
        router = _FakeRouter(["r0", "r1"])
        reader = _FakeReader()
        with _scaler(router, reader) as sc:
            reader.queue_points = {"r0": [(99.0, 3.0), (99.5, 4.0)],
                                   "r1": [(99.0, 1.0), (99.5, 6.0)]}
            qpr, age = sc._trend_queue(100.0)
            assert qpr == pytest.approx((4.0 + 6.0) / 2)
            assert age == pytest.approx(0.5)

    def test_shed_rate_counter_delta_and_reset(self):
        router = _FakeRouter(["r0"])
        reader = _FakeReader()
        with _scaler(router, reader) as sc:
            reader.shed_points = {"f": [(90.0, 10.0), (100.0, 30.0)]}
            assert sc._trend_shed(100.0) == pytest.approx(2.0)
            # restart reset the counter: count from the new value
            reader.shed_points = {"f": [(90.0, 50.0), (100.0, 20.0)]}
            assert sc._trend_shed(100.0) == pytest.approx(2.0)

    def test_scale_down_retires_lifo_with_drain(self):
        router = _FakeRouter(["r0", "r1", "r2"])
        reader = _FakeReader()
        pol = _policy(down_window_s=0.0, down_cooldown_s=0.0,
                      flap_guard_s=0.0)
        with _scaler(router, reader, pol) as sc:
            reader.queue_points = {n: [(99.5, 0.0)] for n in router.names}
            d = sc.tick(now=100.0)
            assert d.action == "down"
            assert router.retired == [("r2", True)]
            assert sc.counters()["scale_downs"] == 1
            # the retire completion was stamped for the flap guard
            assert pol._last_retire_at != float("-inf")

    def test_pick_victim_highest_suffix(self):
        router = _FakeRouter(["r0", "r10", "r2"])
        sc = _scaler(router, _FakeReader())
        try:
            assert sc._pick_victim() == "r10"
        finally:
            sc.close()

    def test_hold_journal_edges_only(self):
        from paddle_tpu import telemetry
        router = _FakeRouter(["r0"])
        reader = _FakeReader()
        with _scaler(router, reader) as sc:
            reader.queue_points = {"r0": [(99.5, 1.0)]}
            sc.tick(now=100.0)
            reader.queue_points = {"r0": [(100.5, 1.0)]}
            sc.tick(now=101.0)
            ev = telemetry.get_journal().recent(kind="autoscale.hold")
            assert len([e for e in ev if e["reason"] == "steady"]) == 1


def test_http_reader_url_parsing():
    r = HttpCollectorReader("http://a:1/, http://b:2")
    assert r.urls == ["http://a:1", "http://b:2"]
    r = HttpCollectorReader(["http://a:1/"])
    assert r.urls == ["http://a:1"]
    with pytest.raises(ValueError):
        HttpCollectorReader("")
    with pytest.raises(ValueError):
        HttpCollectorReader([])


# -- real FleetRouter grow/retire --------------------------------------------


def _feed(n, seed=0):
    rng = np.random.RandomState(seed)
    return {"image": rng.randn(n, 784).astype(np.float32),
            "label": rng.randint(0, 10, (n, 1)).astype(np.int64)}


def _single(feed, i):
    return {k: np.asarray(v)[i:i + 1] for k, v in feed.items()}


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    from paddle_tpu.models import mnist

    d = str(tmp_path_factory.mktemp("autoscale") / "model")
    prog = pt.build(mnist.mlp)
    feed8 = _feed(8)
    params, state = prog.init(jax.random.PRNGKey(0), **feed8)
    pio.save_inference_model(d, prog, jax.tree.map(np.asarray, params),
                             state, feed8, batch_buckets=[4, 8])
    return d


@pytest.fixture()
def fresh_journal():
    from paddle_tpu import telemetry
    telemetry.set_journal(RunJournal())
    yield


class TestRouterElasticity:
    def test_grow_then_retire_drains_in_flight(self, artifact,
                                               fresh_journal):
        router = FleetRouter.spawn(artifact, replicas=1, workers=1,
                                   queue_size=64)
        try:
            assert router.replica_names == ["r0"]
            name = router.grow()
            assert name == "r1"
            assert sorted(router.replica_names) == ["r0", "r1"]
            assert router._counters["replicas_grown"] == 1

            feed8 = _feed(8, seed=3)
            futs = [router.submit(_single(feed8, i % 8))
                    for i in range(24)]
            # retire the newcomer while its share is in flight: every
            # accepted request must still produce a result (drained or
            # transparently rerouted) — zero dropped
            router.retire("r1", drain=True, timeout=60.0)
            assert router.replica_names == ["r0"]
            assert router._counters["replicas_retired"] == 1
            results = [f.result(timeout=60.0) for f in futs]
            assert len(results) == 24
            for r in results:
                assert np.asarray(r["logits"]).shape == (1, 10)
            from paddle_tpu import telemetry
            ev = telemetry.get_journal().recent(kind="fleet.retire")
            assert ev and ev[-1]["replica"] == "r1" and ev[-1]["drain"]
        finally:
            router.close(drain=False)

    def test_retire_unknown_and_last(self, artifact):
        router = FleetRouter.spawn(artifact, replicas=1, workers=1)
        try:
            with pytest.raises(KeyError):
                router.retire("nope")
            with pytest.raises(ValueError):
                router.retire("r0")   # never retire the last replica
            assert router.replica_names == ["r0"]
        finally:
            router.close(drain=False)

    def test_grow_rejects_duplicate_name(self, artifact):
        router = FleetRouter.spawn(artifact, replicas=1, workers=1)
        try:
            with pytest.raises(ValueError):
                router.grow("r0")
        finally:
            router.close(drain=False)


# -- agent dead-children history ---------------------------------------------


class _StubProc:
    def __init__(self, alive):
        self.alive = alive

    def poll(self):
        return None if self.alive else 0


class TestAgentDeadHistory:
    def _service(self, tmp_path, max_dead):
        from paddle_tpu.fleet.agent import AgentService
        return AgentService(str(tmp_path / "agent"), max_dead=max_dead)

    def test_prune_evicts_oldest_dead_only(self, tmp_path):
        svc = self._service(tmp_path, max_dead=3)
        # interleave live and dead children, spawn order = pid order
        for pid in range(1, 9):
            alive = pid % 2 == 0
            svc._procs[pid] = {"name": f"c{pid}",
                               "proc": _StubProc(alive), "addr": ("h", pid)}
        with svc._lock:
            svc._prune_dead_locked()
        # dead pids were 1,3,5,7 — the oldest (1) is evicted, the
        # newest 3 dead are retained; live pids all survive
        assert sorted(svc._procs) == [2, 3, 4, 5, 6, 7, 8]

    def test_live_children_never_evicted_under_churn(self, tmp_path):
        svc = self._service(tmp_path, max_dead=2)
        live_pids = []
        for pid in range(1, 101):
            alive = pid % 10 == 0
            if alive:
                live_pids.append(pid)
            svc._procs[pid] = {"name": f"c{pid}",
                               "proc": _StubProc(alive), "addr": ("h", pid)}
            with svc._lock:
                svc._prune_dead_locked()
            dead_now = [p for p, i in svc._procs.items()
                        if i["proc"].poll() is not None]
            assert len(dead_now) <= 2
        # a hundred spawns later: every live pid is still tracked and
        # the table is bounded to live + max_dead
        assert [p for p in live_pids if p in svc._procs] == live_pids
        assert len(svc._procs) == len(live_pids) + 2
        # the retained dead are the NEWEST dead
        dead_now = sorted(p for p, i in svc._procs.items()
                          if i["proc"].poll() is not None)
        assert dead_now == [98, 99]

    def test_prune_noop_under_cap(self, tmp_path):
        svc = self._service(tmp_path, max_dead=10)
        for pid in (1, 2, 3):
            svc._procs[pid] = {"name": f"c{pid}", "proc": _StubProc(False),
                               "addr": ("h", pid)}
        with svc._lock:
            svc._prune_dead_locked()
        assert sorted(svc._procs) == [1, 2, 3]


# -- shipper flush jitter ----------------------------------------------------


class TestFlushJitter:
    def test_deterministic_and_bounded(self):
        from paddle_tpu.telemetry.shipper import flush_jitter
        for origin in ("r0", "r1", "host-1234", "x"):
            for interval in (0.25, 1.0, 5.0):
                j = flush_jitter(origin, interval)
                assert j == flush_jitter(origin, interval)
                assert 0.0 <= j < 0.25 * interval

    def test_distinct_origins_desync(self):
        from paddle_tpu.telemetry.shipper import flush_jitter
        js = {flush_jitter(f"r{i}", 1.0) for i in range(8)}
        # 8 same-interval shippers land on 8 distinct phases
        assert len(js) == 8

    def test_scales_with_interval(self):
        from paddle_tpu.telemetry.shipper import flush_jitter
        assert flush_jitter("r0", 2.0) == pytest.approx(
            2.0 * flush_jitter("r0", 1.0))
        assert flush_jitter("r0", 1.0, frac=0.5) == pytest.approx(
            2.0 * flush_jitter("r0", 1.0, frac=0.25))

    def test_shipper_instances_pick_up_jitter(self):
        # ctor is connect-free: a bogus addr never dials until flush
        from paddle_tpu.telemetry.shipper import Shipper, flush_jitter
        a = Shipper("127.0.0.1:1", origin="rep-a")
        b = Shipper("127.0.0.1:1", origin="rep-b")
        try:
            assert a.flush_jitter == flush_jitter("rep-a", a.flush_interval)
            assert b.flush_jitter == flush_jitter("rep-b", b.flush_interval)
            assert a.flush_jitter != b.flush_jitter
        finally:
            a.close()
            b.close()


# -- the drill (slow): diurnal replay, 1→N→1, zero dropped -------------------


@pytest.mark.slow
def test_autoscale_drill_passes():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "tools"))
    import fleet_drill
    assert fleet_drill.main(["--drills", "autoscale"]) == 0
