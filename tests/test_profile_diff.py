"""tools/profile_diff.py — bench regression attribution.

The acceptance scenario: two recorded BENCH json files whose train rows
carry ``top_fusions`` tables diff to "THIS fusion got slower" — an
injected slowdown must be localized to the right fusion key. Also
pinned: appeared/vanished fusions (compiler re-fusions), the --config
filter, --json output, and the exit-2 contract on records with nothing
diffable (historical BENCH records predating ``top_fusions``)."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from tools import profile_diff


def _record(configs):
    """A BENCH-suite-shaped record (the thing bench.py emits)."""
    return {"metric": "suite", "value": 0.5, "unit": "MFU",
            "configs": configs}


def _row(step_ms, fusions):
    return {"value": 100.0, "unit": "samples/sec", "step_time_ms": step_ms,
            "top_fusions": [
                {"key": k, "name": k.split("|")[0], "op": k.split("|")[0],
                 "kind": "loop", "computation": "main", "in_loop": False,
                 "flops": fl, "bytes": by, "out_bytes": by // 2,
                 "source_ops": [k.split("|")[1]], "cost_frac": cf}
                for (k, cf, fl, by) in fusions]}


def test_injected_slowdown_localized_to_right_fusion(tmp_path):
    """Run A: three fusions at 50/30/20% of a 10ms step. Run B: the
    matmul fusion tripled (a regression injected into that one fusion:
    its cost share AND the step time rise). The diff must rank it
    slowest — not the fusions whose absolute share merely drifted."""
    a = _record({"mnist_mlp_train": _row(10.0, [
        ("dot|dense/matmul|f32[128,200]", 0.50, 2e9, 4_000_000),
        ("fusion|mlp/relu|f32[128,200]", 0.30, 1e6, 2_000_000),
        ("reduce|loss/sum|f32[]", 0.20, 5e5, 1_000_000),
    ])})
    b = _record({"mnist_mlp_train": _row(20.0, [
        ("dot|dense/matmul|f32[128,200]", 0.75, 6e9, 12_000_000),
        ("fusion|mlp/relu|f32[128,200]", 0.15, 1e6, 2_000_000),
        ("reduce|loss/sum|f32[]", 0.10, 5e5, 1_000_000),
    ])})
    fa, fb = tmp_path / "BENCH_rA.json", tmp_path / "BENCH_rB.json"
    fa.write_text(json.dumps(a))
    fb.write_text(json.dumps(b))

    diff = profile_diff.diff_records(a, b)
    d = diff["configs"]["mnist_mlp_train"]
    assert d["step_delta_ms"] == 10.0
    assert d["slowest"] == "dot|dense/matmul|f32[128,200]"
    top = d["fusions"][0]
    # 0.50*10ms -> 0.75*20ms: +10ms of the +10ms regression
    assert top["est_ms_a"] == 5.0 and top["est_ms_b"] == 15.0
    assert top["delta_ms"] == 10.0 and top["status"] == "common"
    assert top["flops_b"] > top["flops_a"]  # program-level evidence
    # the relu fusion stayed flat in absolute terms: 3ms -> 3ms
    relu = next(e for e in d["fusions"]
                if e["key"].startswith("fusion|mlp/relu"))
    assert relu["delta_ms"] == 0.0

    # the CLI over the two recorded files agrees and exits 0
    rc = profile_diff.main([str(fa), str(fb)])
    assert rc == 0


def test_appeared_and_vanished_fusions(capsys):
    a = _record({"m_train": _row(10.0, [
        ("dot|a|f32[8,8]", 0.9, 1e6, 1000),
        ("fusion|gone|f32[4]", 0.1, 1e3, 100)])})
    b = _record({"m_train": _row(10.0, [
        ("dot|a|f32[8,8]", 0.8, 1e6, 1000),
        ("fusion|new|f32[16]", 0.2, 2e3, 200)])})
    d = profile_diff.diff_records(a, b)["configs"]["m_train"]
    status = {e["key"]: e["status"] for e in d["fusions"]}
    assert status["fusion|gone|f32[4]"] == "vanished"
    assert status["fusion|new|f32[16]"] == "appeared"
    assert status["dot|a|f32[8,8]"] == "common"
    out = profile_diff.render(profile_diff.diff_records(a, b))
    assert "m_train" in out and "appeared" in out and "vanished" in out


def test_config_filter_and_json_output(tmp_path, capsys):
    rec = _record({"a_train": _row(1.0, [("dot|x|f32[2]", 1.0, 1.0, 8)]),
                   "b_train": _row(2.0, [("dot|y|f32[2]", 1.0, 1.0, 8)])})
    fa, fb = tmp_path / "a.json", tmp_path / "b.json"
    fa.write_text(json.dumps(rec))
    fb.write_text(json.dumps(rec))
    rc = profile_diff.main([str(fa), str(fb), "--config", "b_train",
                            "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert list(doc["configs"]) == ["b_train"]
    assert doc["configs"]["b_train"]["step_delta_ms"] == 0.0


def test_historical_records_without_top_fusions_exit_2(tmp_path, capsys):
    """Pre-PR-6 BENCH records carry no top_fusions: the CLI must say
    'nothing compared' (exit 2), not fake a clean diff. Exercised
    against the repo's real recorded BENCH files."""
    here = os.path.join(os.path.dirname(__file__), os.pardir)
    r04, r05 = (os.path.join(here, f"BENCH_r0{n}.json") for n in (4, 5))
    if not (os.path.exists(r04) and os.path.exists(r05)):
        pytest.skip("recorded BENCH files not present")
    rc = profile_diff.main([r04, r05])
    assert rc == 2


def test_rows_accepts_raw_envelope_and_bare_row():
    row = _row(1.0, [("dot|x|f32[2]", 1.0, 1.0, 8)])
    assert profile_diff._rows({"result": row}) == {"<row>": row}
    assert profile_diff._rows(row) == {"<row>": row}
    assert profile_diff._rows({"configs": {"x_train": {"value": 1}}}) == {}


def test_fusion_profile_row_diffs_via_avg_step_ms():
    """The fusion_profile suite row records avg_step_ms rather than
    step_time_ms; the diff accepts either clock."""
    row_a = {"avg_step_ms": 4.0,
             "top_fusions": [{"key": "dot|q|f32[4]", "cost_frac": 1.0,
                              "flops": 1.0, "bytes": 8, "source_ops": []}]}
    row_b = {"avg_step_ms": 8.0,
             "top_fusions": [{"key": "dot|q|f32[4]", "cost_frac": 1.0,
                              "flops": 1.0, "bytes": 8, "source_ops": []}]}
    d = profile_diff.diff_rows(row_a, row_b)
    assert d["step_delta_ms"] == 4.0
    assert d["fusions"][0]["delta_ms"] == 4.0
