"""Book-model parity: label_semantic_roles (BiLSTM-CRF) and
recommender_system (movielens towers) train end-to-end on their
synthetic datasets."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import optimizer as opt
from paddle_tpu.data import datasets
from paddle_tpu.models import recommender, srl


def _batches(reader, batch_size, names):
    buf = []
    for sample in reader():
        buf.append(sample)
        if len(buf) == batch_size:
            yield {n: np.stack([s[i] for s in buf]) for i, n in enumerate(names)}
            buf = []


def test_srl_crf_learns():
    vocab, labels = 200, 6
    model = pt.build(srl.make_model(vocab_size=vocab, num_labels=labels,
                                    word_dim=16, hidden_dim=32, depth=2))
    reader = datasets.conll05(vocab_size=vocab, num_labels=labels, seq_len=16,
                              synthetic_size=2048)
    names = ["word_ids", "mark_ids", "label", "lengths"]
    tr = pt.Trainer(model, opt.Adam(5e-3), loss_name="loss",
                    fetch_list=["loss", "acc"])
    batches = list(_batches(reader, 32, names))
    tr.startup(sample_feed=batches[0])
    first = float(tr.step(batches[0])["loss"])
    for _ in range(3):
        for b in batches:
            out = tr.step(b)
    last, acc = float(out["loss"]), float(out["acc"])
    assert last < first * 0.6, (first, last)
    assert acc > 0.5, acc          # chance = 1/6


def test_recommender_learns():
    model = pt.build(recommender.make_model(num_users=100, num_movies=80,
                                            title_vocab=50, emb_dim=16, fc_dim=32))
    reader = datasets.movielens(num_users=100, num_movies=80, title_vocab=50,
                                synthetic_size=1024)
    names = ["user_id", "gender_id", "age_id", "job_id", "movie_id",
             "category_ids", "title_ids", "score"]
    tr = pt.Trainer(model, opt.Adam(1e-2), loss_name="loss",
                    fetch_list=["loss", "pred"])
    batches = list(_batches(reader, 64, names))
    tr.startup(sample_feed=batches[0])
    first = float(tr.step(batches[0])["loss"])
    for _ in range(6):
        for b in batches:
            out = tr.step(b)
    last = float(out["loss"])
    assert last < first * 0.7, (first, last)
    pred = np.asarray(out["pred"])
    assert np.all(np.isfinite(pred))
