"""Enforce-style error checking.

TPU-native analog of the reference's ``PADDLE_ENFORCE*`` macro family
(reference: paddle/fluid/platform/enforce.h). Instead of C++ macros with
captured backtraces we raise rich Python exceptions; JAX tracebacks carry
the stack.
"""

from __future__ import annotations

from typing import Any, NoReturn


class EnforceError(RuntimeError):
    """Framework invariant violation (PADDLE_ENFORCE analog)."""


class NotFoundError(EnforceError, KeyError):
    """A named variable/parameter was not found in the scope."""


class ShapeError(EnforceError, ValueError):
    """Shape mismatch between declared and actual tensors."""


def enforce(cond: Any, msg: str = "", *args: Any) -> None:
    """Raise :class:`EnforceError` unless ``cond`` is truthy.

    Mirrors PADDLE_ENFORCE(cond, fmt, ...) — enforce.h.
    """
    if not cond:
        raise EnforceError(msg % args if args else msg)


def enforce_eq(a: Any, b: Any, msg: str = "") -> None:
    if a != b:
        raise EnforceError(f"Enforce failed: {a!r} != {b!r}. {msg}")


def enforce_gt(a: Any, b: Any, msg: str = "") -> None:
    if not a > b:
        raise EnforceError(f"Enforce failed: {a!r} <= {b!r}. {msg}")


def not_found(msg: str) -> NoReturn:
    raise NotFoundError(msg)
