"""VGG-16/19 — benchmark/fluid/models/vgg.py analog (conv blocks with
BN + dropout fc head, the img_conv_group pattern from fluid nets.py)."""

from __future__ import annotations

from .. import layers as L
from ..framework import name_scope
from ..metrics import accuracy

CFG = {16: (2, 2, 3, 3, 3), 19: (2, 2, 4, 4, 4)}


def conv_block(x, num_filter, groups):
    for _ in range(groups):
        x = L.conv2d(x, num_filter, 3, padding=1, act=None, bias_attr=False)
        x = L.batch_norm(x, act="relu")
    return L.pool2d(x, pool_size=2, pool_stride=2, pool_type="max")


def make_model(depth=16, class_num=10, fc_dim=512):
    groups = CFG[depth]

    def vgg(image, label):
        x = image
        for i, (nf, g) in enumerate(zip((64, 128, 256, 512, 512), groups)):
            with name_scope(f"block{i}"):
                x = conv_block(x, nf, g)
        x = L.flatten(L.to_chw_order(x), axis=1)
        x = L.dropout(x, 0.5)
        x = L.fc(x, fc_dim, act=None)
        x = L.batch_norm(x, act="relu")
        x = L.dropout(x, 0.5)
        x = L.fc(x, fc_dim, act="relu")
        logits = L.fc(x, class_num)
        loss = L.mean(L.softmax_with_cross_entropy(logits, label))
        return {"loss": loss, "acc": accuracy(logits, label), "logits": logits}

    return vgg
