"""Cross-process serving fleet acceptance suite: out-of-process
replicas over the framed transport, surviving REAL kills and
partitions (no in-process stand-ins — SIGKILL is SIGKILL, a partition
is a blackholed TCP link).

The acceptance contracts:

  * a remote fleet serves bit-identically to a local pad-alone
    ``Predictor.run`` (same artifact, same buckets, over the wire);
  * SIGKILL of a replica process under load loses ZERO
    accepted-but-undispatched requests (transparently rerouted) and
    surfaces ``ReplicaDied`` exactly once for dispatched ones;
    ``replace()`` respawns a fresh process from the artifact;
  * a reply lost on a real half-open connection (partitioned link,
    process alive) surfaces ``ReplicaDied`` exactly once and is NEVER
    resent — the replica's journal shows at most one submit for the
    span (mirroring ``PSClient.push``'s ``PushUndelivered``);
  * health probes are bounded: a probe that never returns (wedged
    in-process ``health()``, partitioned remote) marks the replica
    unavailable within the probe timeout and the router stays
    responsive;
  * a slow-but-alive replica (probe latency past ``slow_after``) is
    DEMOTED below healthy replicas, not treated as dead;
  * one trace id crosses the process boundary: the front door mints
    the span, the wire trace token hands it to the replica, and both
    processes' journals carry it (``ship_journals`` merges them);
  * SLO-aware batch sizing: at low load the policy picks the smallest
    covering bucket with zero idle wait (p50 drops), at saturation the
    plan is the legacy largest-bucket fill (throughput untouched);
  * ``tools/fleet_drill.py`` passes its process-level drills (pkill +
    partition during rolling reload, exit 0).
"""

import os
import sys
import time

import numpy as np
import pytest

import jax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu import serving, telemetry
from paddle_tpu.fleet import BatchPolicy, FleetRouter
from paddle_tpu.fleet import batching as fbatch
from paddle_tpu.fleet import remote as fremote
from paddle_tpu.serving import (DeadlineExceeded, PredictorServer,
                                ReloadFailed, ReplicaDied, ServerClosed,
                                ServerOverloaded)
from paddle_tpu.telemetry.journal import RunJournal
from paddle_tpu.testing import faults

REMOTE_KW = dict(probe_timeout=0.5, down_cooldown=0.4, submit_timeout=3.0,
                 connect_timeout=1.0, reload_timeout=12.0)


def _feed(n, seed=0):
    rng = np.random.RandomState(seed)
    return {"image": rng.randn(n, 784).astype(np.float32),
            "label": rng.randint(0, 10, (n, 1)).astype(np.int64)}


def _single(feed, i):
    return {k: np.asarray(v)[i % 8:i % 8 + 1] for k, v in feed.items()}


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    from paddle_tpu.models import mnist

    d = str(tmp_path_factory.mktemp("rfleet") / "model")
    prog = pt.build(mnist.mlp)
    feed8 = _feed(8)
    params, state = prog.init(jax.random.PRNGKey(0), **feed8)
    pio.save_inference_model(d, prog, jax.tree.map(np.asarray, params),
                             state, feed8, batch_buckets=[4, 8])
    return {"dir": d, "prog": prog, "params": params, "state": state,
            "feed8": feed8}


@pytest.fixture()
def fresh_journal():
    old = telemetry.set_journal(RunJournal())
    try:
        yield telemetry.get_journal()
    finally:
        telemetry.set_journal(old)


# -- pure units: wire packing, typed errors, SLO plan -------------------------


def test_pack_unpack_roundtrip():
    feed = {"image": np.arange(8, dtype=np.float32).reshape(2, 4),
            "label": np.array([[3], [7]], dtype=np.int64),
            "scalar": np.float32(2.5)}
    meta, payload = fremote.pack_tree(feed)
    back = fremote.unpack_tree(meta, payload)
    assert sorted(back) == sorted(feed)
    for k in feed:
        np.testing.assert_array_equal(np.asarray(back[k]),
                                      np.asarray(feed[k]))
        assert back[k].dtype == np.asarray(feed[k]).dtype
    single = np.arange(3, dtype=np.int32)
    np.testing.assert_array_equal(
        fremote.unpack_tree(*fremote.pack_tree(single)), single)
    tup = (np.zeros((2, 2), np.float32), np.ones(3, np.float64))
    back_t = fremote.unpack_tree(*fremote.pack_tree(tup))
    assert isinstance(back_t, tuple) and len(back_t) == 2


def test_remote_error_roundtrip():
    from paddle_tpu.resilience import CheckpointCorrupt

    cases = [
        pio.InvalidRequest("image", "shape drift"),
        ServerOverloaded(9, 8),
        serving.CircuitOpen(1.25),
        ReloadFailed("/tmp/x", "canary failed"),
        CheckpointCorrupt("/tmp/y", "torn write"),
        DeadlineExceeded("too late"),
        serving.WorkerHung("wedged"),
        ServerClosed("closed"),
        ReplicaDied("gone"),
    ]
    for e in cases:
        name, detail = fremote.error_payload(e)
        back = fremote.build_remote_error(name, detail)
        assert type(back) is type(e), (e, back)
    back = fremote.build_remote_error("SomethingNovel", {"message": "m"})
    assert isinstance(back, serving.ServingError)
    over = fremote.build_remote_error(*fremote.error_payload(
        ServerOverloaded(9, 8)))
    assert (over.queue_depth, over.capacity) == (9, 8)


def test_batch_policy_plan_units():
    buckets = [4, 8, 16]
    legacy = BatchPolicy(max_wait_ms=5.0)
    assert legacy.plan(0, 1, buckets) == (16, 5.0)
    assert legacy.plan(100, 1, buckets) == (16, 5.0)
    slo = BatchPolicy(max_wait_ms=5.0, slo_queue_threshold=4)
    # low load: smallest covering bucket, zero idle wait
    assert slo.plan(0, 1, buckets) == (4, 0.0)
    assert slo.plan(2, 1, buckets) == (4, 0.0)
    assert slo.plan(3, 3, buckets) == (8, 0.0)
    # saturated: the legacy plan, bit-for-bit — throughput untouched
    assert slo.plan(4, 1, buckets) == legacy.plan(4, 1, buckets)
    assert slo.plan(50, 1, buckets) == legacy.plan(50, 1, buckets)
    # the target never exceeds the largest bucket
    assert slo.plan(3, 16, buckets) == (16, 0.0)


def test_slo_policy_drops_low_qps_latency(artifact):
    """A lone request at low QPS must NOT pay the coalescer's idle
    hold when the policy is SLO-aware (the full-bucket wait was the
    p50 cost the ROADMAP named)."""
    base = pio.load_inference_model(artifact["dir"])
    wait_ms = 150.0

    def p50(policy):
        srv = PredictorServer(base.clone(), workers=1, queue_size=8,
                              batch_policy=policy, warmup=False)
        try:
            srv.run(_single(artifact["feed8"], 0), timeout=30)  # warm
            lats = []
            for i in range(3):
                t0 = time.monotonic()
                srv.run(_single(artifact["feed8"], i), timeout=30)
                lats.append(time.monotonic() - t0)
            return sorted(lats)[1]
        finally:
            srv.close(drain=False)

    slow = p50(BatchPolicy(max_wait_ms=wait_ms))
    fast = p50(BatchPolicy(max_wait_ms=wait_ms, slo_queue_threshold=2))
    assert slow >= wait_ms / 1e3 * 0.8, (slow, fast)
    assert fast < wait_ms / 1e3 * 0.5, (slow, fast)


def test_journal_subscribe_and_ingest():
    j = RunJournal(run_id="local")
    seen = []
    sid = j.subscribe(seen.append)
    j.emit("x.one", span="s1")
    assert [e["kind"] for e in seen] == ["x.one"]
    j.unsubscribe(sid)
    j.emit("x.two")
    assert len(seen) == 1
    foreign = [{"run": "remoterun", "seq": 7, "t": 1.0,
                "kind": "serving.submit", "span": "abc"}]
    assert j.ingest(foreign, origin="r1") == 1
    got = [e for e in j.recent() if e.get("origin") == "r1"]
    assert got and got[0]["run"] == "remoterun" and got[0]["seq"] == 7
    # this journal's own seq was NOT consumed by the shipped event
    assert j.seq == 2
    assert j.ingest([{"no": "kind"}]) == 0
    # subscribers are a live channel, NOT a sink: per-kind sampling
    # must not silence them — the replica wire's DISPATCHED ordering
    # hangs off a serving.dispatch subscriber even under
    # PDTPU_JOURNAL_SAMPLE=serving=0
    js = RunJournal(sample={"serving": 0.0})
    seen_s = []
    js.subscribe(seen_s.append)
    js.emit("serving.dispatch", span="s1")
    assert [e["kind"] for e in seen_s] == ["serving.dispatch"]
    assert js.recent() == [] and js.dropped_sampled == 1


# -- the remote fleet ---------------------------------------------------------


@pytest.fixture(scope="module")
def remote_fleet(artifact):
    router = FleetRouter.spawn(
        artifact["dir"], replicas=2, remote=True,
        remote_kw=dict(REMOTE_KW), workers=1, queue_size=16,
        golden_feed=artifact["feed8"],
        batch_policy=BatchPolicy(max_wait_ms=2.0))
    yield router
    router.close(drain=False, timeout=10)


def test_remote_fleet_serves_bit_identical(remote_fleet, artifact):
    base = pio.load_inference_model(artifact["dir"])
    for i in range(4):
        feed = _single(artifact["feed8"], i)
        out = remote_fleet.run(feed, timeout=60)
        padded = {k: np.concatenate(
            [v, np.zeros((3,) + np.asarray(v).shape[1:],
                         np.asarray(v).dtype)])
            for k, v in feed.items()}
        ref = base.run(padded)
        np.testing.assert_array_equal(np.asarray(out["logits"]),
                                      np.asarray(ref["logits"])[:1])
    h = remote_fleet.health()
    assert h["state"] == "ready" and h["replicas_ready"] == 2
    rep = remote_fleet.report()
    assert sorted(rep["replicas"]) == ["r0", "r1"]
    assert all(r["compiles_since_warmup"] == 0
               for r in rep["replicas"].values())


def test_remote_metrics_aggregation(remote_fleet):
    from paddle_tpu.telemetry.registry import validate_families

    fams = remote_fleet.metrics_families()
    by_name = {f.name: f for f in fams}
    assert "paddle_tpu_serving_submitted_total" in by_name
    replicas = {lab.get("replica")
                for f in fams for lab, _ in f.samples}
    assert {"r0", "r1", "router"} <= replicas
    assert validate_families(fams) == []


def test_cross_process_journal_one_trace_id(remote_fleet, fresh_journal):
    """Satellite: one trace id from front-door submit through remote
    dispatch to completion, asserted against BOTH processes'
    journals."""
    p = remote_fleet.submit(_single(_feed(8), 0))
    p.result(timeout=60)
    span = p.span
    assert span
    # parent-side journal: the front door's submit event carries it
    parent_kinds = {e["kind"] for e in fresh_journal.recent(span=span)}
    assert "fleet.remote_submit" in parent_kinds
    # replica-side journal (pulled over the same framed link): the
    # serving lifecycle carries the SAME id
    rep = remote_fleet.replica(p.replica)
    events = rep.journal_events()
    rep_kinds = {e["kind"] for e in events if e.get("span") == span}
    assert {"serving.submit", "serving.dispatch",
            "serving.complete"} <= rep_kinds, rep_kinds
    # shipping merges them into the local ring, origin-tagged, spans
    # intact — one ring now holds the cross-process timeline
    assert remote_fleet.ship_journals() > 0
    shipped = [e for e in fresh_journal.recent(span=span)
               if e.get("origin")]
    assert {"serving.submit", "serving.complete"} <= {
        e["kind"] for e in shipped}
    # incremental: a second ship with no new replica traffic is empty
    assert remote_fleet.ship_journals() == 0


def test_sigkill_zero_drop_and_at_most_once(artifact):
    """Acceptance drill core, pinned directly: SIGKILL a replica
    process with requests in flight — every accepted request either
    completes (rerouted transparently if never dispatched) or surfaces
    ReplicaDied exactly once; ServerClosed NEVER reaches the caller;
    replace() respawns a process and health recovers."""
    router = FleetRouter.spawn(
        artifact["dir"], replicas=2, remote=True,
        remote_kw=dict(REMOTE_KW), workers=1, queue_size=16,
        golden_feed=artifact["feed8"],
        batch_policy=BatchPolicy(max_wait_ms=2.0))
    try:
        for _ in range(2):
            router.run(_single(artifact["feed8"], 0), timeout=60)
        pending = [router.submit(_single(artifact["feed8"], i))
                   for i in range(24)]
        victim = pending[0].replica
        faults.kill_process(router.replica(victim))
        outcomes = {"ok": 0}
        for p in pending:
            try:
                p.result(timeout=60)
                outcomes["ok"] += 1
            except BaseException as e:
                outcomes[type(e).__name__] = \
                    outcomes.get(type(e).__name__, 0) + 1
        # zero drops: only clean completions and at-most-once surfaces
        assert set(outcomes) <= {"ok", "ReplicaDied"}, outcomes
        assert outcomes["ok"] >= 1
        # the kill was mid-load: the router rerouted in-queue work
        assert router.report()["rerouted"] + outcomes["ok"] >= 1
        state = router.health()["state"]
        assert state in ("degraded", "unavailable"), state
        router.replace(victim)   # respawn a fresh process
        h = router.health()
        assert h["state"] == "ready", h
        assert router.replica(victim).proc.poll() is None
        router.run(_single(artifact["feed8"], 1), timeout=60)
    finally:
        router.close(drain=False, timeout=10)


def test_half_open_reply_lost_surfaces_once_never_resent(artifact,
                                                         fresh_journal):
    """The at-most-once contract re-proven on a REAL half-open
    connection: the submit leaves the socket, the partition eats the
    reply, the process stays alive → ReplicaDied exactly once, and the
    replica's journal shows the request was never resent (at most one
    submit for the span — delivered late by the healed link, not
    duplicated)."""
    # the long coalescer hold (max_wait_ms=2500, no SLO threshold)
    # gives the stall half of the test a deterministic window where a
    # request is ACCEPTED but no lifecycle bytes flow yet
    proc = fremote.ReplicaProcess(
        artifact["dir"], server_kw=dict(
            workers=1, queue_size=16, golden_feed=artifact["feed8"],
            batch_policy=BatchPolicy(max_wait_ms=2500.0)))
    proxy = None
    try:
        proc.wait_ready()
        proxy = faults.LinkProxy(proc.addr)
        rep = fremote.RemoteReplica(
            proxy.addr, proc=proc, name="r0",
            **dict(REMOTE_KW, submit_timeout=0.6))
        rep.run(_single(artifact["feed8"], 0), timeout=60)  # link works
        faults.partition(proxy)
        t0 = time.monotonic()
        with pytest.raises(ReplicaDied, match="never resent|not resending"):
            rep.submit(_single(artifact["feed8"], 1))
        assert time.monotonic() - t0 < 5.0
        # the span the front door minted for the lost submit
        lost = [e for e in fresh_journal.recent(kind="fleet.remote_submit")]
        span = lost[-1]["span"]
        faults.heal(proxy)
        time.sleep(4.0)   # the healed link delivers the buffered bytes
        inspect = fremote.RemoteReplica(proc.addr, proc=proc,
                                        **dict(REMOTE_KW))
        events = inspect.journal_events()
        submits = [e for e in events if e["kind"] == "serving.submit"
                   and e.get("span") == span]
        assert len(submits) <= 1, submits   # at-most-once on the wire
        assert proc.poll() is None          # the replica never died
        # -- the silent-stall half: ACCEPTED, then the partition eats
        # the lifecycle. The socket never errors — the client must
        # detect the stall (submit_timeout of silence), probe, and
        # classify at-most-once instead of hanging to the deadline.
        p = rep.submit(_single(artifact["feed8"], 2))   # accepted (OK id)
        faults.partition(proxy)
        t0 = time.monotonic()
        with pytest.raises(ReplicaDied):
            p.result(timeout=30)
        assert time.monotonic() - t0 < 10.0
        assert proc.poll() is None          # still a partition, not death
    finally:
        if proxy is not None:
            proxy.close()
        proc.stop()


def test_bounded_probe_partitioned_replica(artifact):
    """Satellite fix: health aggregation tolerates a probe that never
    returns — the partitioned replica is marked unavailable within the
    bound and the router keeps routing."""
    procs = [fremote.ReplicaProcess(
        artifact["dir"], server_kw=dict(workers=1, queue_size=16))
        for _ in range(2)]
    proxy = None
    try:
        for p in procs:
            p.wait_ready()
        proxy = faults.LinkProxy(procs[1].addr)
        reps = {
            "good": fremote.RemoteReplica(procs[0].addr, proc=procs[0],
                                          name="good", **REMOTE_KW),
            "cut": fremote.RemoteReplica(proxy.addr, proc=procs[1],
                                         name="cut", **REMOTE_KW),
        }
        router = FleetRouter(reps, dirname=artifact["dir"],
                             probe_timeout=0.8, remote=True)
        faults.partition(proxy)
        t0 = time.monotonic()
        h = router.health()
        elapsed = time.monotonic() - t0
        assert elapsed < 4.0, elapsed
        assert h["state"] == "degraded", h
        assert not h["replicas"]["cut"]["ready"]
        assert h["replicas"]["cut"]["state"].startswith(
            ("unreachable", "probe_timeout"))
        # routing stays responsive: traffic lands on the good replica
        out = router.run(_single(artifact["feed8"], 0), timeout=60)
        assert "logits" in out
        assert router.report()["routed"]["good"] >= 1
        router.close(drain=False, timeout=5)
    finally:
        if proxy is not None:
            proxy.close()
        for p in procs:
            p.stop()


def test_wedged_inprocess_health_probe_bounded(artifact):
    """The same satellite for an ADOPTED in-process replica whose
    health() never returns: the router's own probe bound abandons it
    and stays responsive."""
    import threading

    base = pio.load_inference_model(artifact["dir"])
    good = PredictorServer(base, workers=1, queue_size=16, warmup=False)

    class Wedged:
        def health(self):
            threading.Event().wait()   # never returns

        def close(self, **kw):
            pass

        def kill(self, **kw):
            pass

        def repin_compiles(self):
            pass

    router = FleetRouter({"good": good, "wedged": Wedged()},
                         probe_timeout=0.3)
    try:
        t0 = time.monotonic()
        h = router.health()
        assert time.monotonic() - t0 < 2.0
        assert h["replicas"]["wedged"]["state"] == "probe_timeout"
        assert h["state"] == "degraded"
        out = router.run(_single(artifact["feed8"], 0), timeout=30)
        assert "logits" in out
    finally:
        router.close(drain=False, timeout=5)


def test_slow_link_probe_latency_demotion(artifact):
    """Graceful degradation: a slow-but-alive replica (probe latency
    past slow_after) is demoted below healthy ones — traffic prefers
    the fast replica, but the slow one still counts as ready."""
    procs = [fremote.ReplicaProcess(
        artifact["dir"], server_kw=dict(workers=1, queue_size=16))
        for _ in range(2)]
    proxy = None
    try:
        for p in procs:
            p.wait_ready()
        proxy = faults.LinkProxy(procs[1].addr)
        kw = dict(REMOTE_KW, slow_after=0.05, health_ttl=0.0)
        reps = {
            "fast": fremote.RemoteReplica(procs[0].addr, proc=procs[0],
                                          name="fast", **kw),
            "slow": fremote.RemoteReplica(proxy.addr, proc=procs[1],
                                          name="slow", **kw),
        }
        faults.slow_link(proxy, 80.0)
        router = FleetRouter(reps, dirname=artifact["dir"], remote=True)
        for i in range(4):
            router.run(_single(artifact["feed8"], i), timeout=60)
        routed = router.report()["routed"]
        assert routed["fast"] == 4 and routed["slow"] == 0, routed
        assert router.health()["replicas"]["slow"]["ready"]
        assert router.health()["replicas"]["slow"]["slow"] is True
        router.close(drain=False, timeout=5)
    finally:
        if proxy is not None:
            proxy.close()
        for p in procs:
            p.stop()


def test_remote_rolling_reload_and_partition_rollback(artifact, tmp_path):
    """Rolling reload across processes coordinated by artifact
    generation — and the acceptance partition drill pinned directly: a
    TCP partition mid-rollout rolls the swapped replicas back to the
    previous artifact with the router's dirname unchanged."""
    params = jax.tree.map(np.asarray, artifact["params"])
    d_v2 = str(tmp_path / "v2")
    pio.save_inference_model(
        d_v2, artifact["prog"], jax.tree.map(lambda v: v * 0.5, params),
        artifact["state"], artifact["feed8"], batch_buckets=[4, 8])
    server_kw = dict(workers=1, queue_size=16,
                     golden_feed=artifact["feed8"])
    procs = [fremote.ReplicaProcess(artifact["dir"], server_kw=server_kw)
             for _ in range(2)]
    proxy = None
    try:
        for p in procs:
            p.wait_ready()
        proxy = faults.LinkProxy(procs[1].addr)
        # a long health TTL makes the partition-mid-rollout timing
        # deterministic: the rollout's liveness scan reads the cached
        # pre-partition snapshot, so r1 IS in the rollout order and
        # the failure provably lands on its partitioned RELOAD
        kw = dict(REMOTE_KW, health_ttl=30.0, reload_timeout=8.0)
        reps = {
            "r0": fremote.RemoteReplica(procs[0].addr, proc=procs[0],
                                        name="r0", **kw),
            "r1": fremote.RemoteReplica(proxy.addr, proc=procs[1],
                                        name="r1", **kw),
        }
        router = FleetRouter(reps, dirname=artifact["dir"],
                             server_kw=server_kw, probe_timeout=1.0,
                             remote=True, remote_kw=dict(REMOTE_KW))
        # a clean rolling reload first: every process swaps
        gens = router.reload(d_v2)
        assert sorted(gens) == ["r0", "r1"]
        assert all(g == 2 for g in gens.values()), gens
        # now partition r1 and roll back to the original artifact:
        # the rollout must fail typed and r0 must roll back (gen 4:
        # 2 → 3 on the v1 swap → 4 on the rollback to v2 — the canary
        # swapped to v1 before r1's reload hit the partition)
        router.health()          # refresh the cache pre-partition
        faults.partition(proxy)
        with pytest.raises(ReloadFailed, match="rolled back"):
            router.reload(artifact["dir"])
        assert router.dirname == d_v2          # previous artifact kept
        assert reps["r0"].generation == 4       # v1 swap + rollback
        out = router.run(_single(artifact["feed8"], 0), timeout=60)
        assert "logits" in out                  # fleet still serving
        faults.heal(proxy)
        router.replace("r1")                    # fresh process, v2
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and \
                router.health()["state"] != "ready":
            time.sleep(0.1)
        assert router.health()["state"] == "ready"
        router.close(drain=False, timeout=10)
    finally:
        if proxy is not None:
            proxy.close()
        for p in procs:
            p.stop()


def test_fleet_drill_process_drills_pass():
    """The process-level drills (SIGKILL mid-stream at ~3x saturation;
    partition during rolling reload) hold their contracts end to end
    (exit 0; exit-code contract 0/2/3 preserved)."""
    from tools import fleet_drill

    assert fleet_drill.main(["--drills", "pkill,partition",
                             "--replicas", "2", "--requests", "24"]) == 0
