"""Device mesh construction.

The TPU-native replacement for the reference's device-list + NCCL
communicator plumbing (parallel_executor.cc:94-107 NCCLContextMap,
nccl_helper.h:81): a named ``jax.sharding.Mesh`` over which all
parallelism is expressed as sharding annotations. Axis names:

- ``dp``   — data parallel (allreduce-mode analog, build_strategy.h:55 kAllReduce)
- ``fsdp`` — data parallel with sharded params/optimizer state
             (reduce-mode + pserver param-slicing analog — the ZeRO-ish
             capability of distribute_transpiler.py:81 slice_variable)
- ``tp``   — tensor parallel (gap-fill per SURVEY §2.2: absent in reference)
- ``sp``   — sequence/context parallel (ring attention; gap-fill)
- ``pp``   — pipeline stages (gap-fill)
- ``ep``   — expert / embedding-shard parallel (distributed-lookup-table
             analog, distribute_transpiler.py:1100)

Multi-host: ``initialize()`` wraps jax.distributed.initialize — the
gen_nccl_id_op.cc:31 bootstrap analog (coordinator address instead of
broadcasting an ncclUniqueId over gRPC).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DP, FSDP, TP, SP, PP, EP = "dp", "fsdp", "tp", "sp", "pp", "ep"
DATA_AXES = (DP, FSDP)  # axes the batch dimension is sharded over


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Create a named mesh. ``axes`` maps axis name → size; a -1 size is
    inferred from the device count. Default: all devices on ``dp``.

    Axis order follows the dict order; put the fastest-varying
    (innermost, highest-bandwidth ICI) axis last — conventionally ``tp``
    — so tensor-parallel collectives ride nearest-neighbor links.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axes:
        axes = {DP: n}
    axes = dict(axes)
    unknown = [k for k, v in axes.items() if v == -1]
    if unknown:
        known = int(np.prod([v for v in axes.values() if v != -1]))
        if n % known:
            raise ValueError(f"cannot infer axis {unknown[0]}: {n} devices not divisible by {known}")
        axes[unknown[0]] = n // known
    total = int(np.prod(list(axes.values())))
    if total != n:
        raise ValueError(f"mesh axes {axes} need {total} devices, have {n}")
    arr = np.asarray(devices).reshape(tuple(axes.values()))
    return Mesh(arr, tuple(axes.keys()))


def pvary(x, axis_names):
    """Mark ``x`` as device-varying over ``axis_names`` inside shard_map
    (vma bookkeeping for mixing replicated operands with sharded ones).
    Wraps lax.pcast with fallback to the deprecated lax.pvary."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_names, to="varying")
    return jax.lax.pvary(x, axis_names)


def data_axis_names(mesh: Mesh) -> tuple:
    return tuple(a for a in mesh.axis_names if a in DATA_AXES)


def data_parallel_size(mesh: Mesh) -> int:
    return int(np.prod([mesh.shape[a] for a in data_axis_names(mesh)] or [1]))


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap (gen_nccl_id / jax.distributed.initialize
    analog). No-op when args are absent and env vars are unset."""
    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    jax.distributed.initialize(**kwargs)
