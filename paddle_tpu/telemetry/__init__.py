"""paddle_tpu.telemetry — unified process telemetry.

Before this package every subsystem reported into its own ad-hoc dict
(``PipelineMetrics.report()``, ``ServingMetrics.report()``,
``trainer.profile_report()``, bare ``pushes_lost`` attributes) with no
common export format, no cross-component correlation, and nothing
captured at the moment of a crash. Telemetry is the one surface an
operator points Prometheus (and post-mortem tooling) at:

- :mod:`registry` — the process-wide **metrics registry** (counters,
  gauges, log-bucket histograms with labels; scrape-time collectors
  for zero hot-path cost) that Trainer/feeder/guard/checkpoint,
  async-PS client/server counters, and serving queue/latency/breaker
  state all publish into, under the
  ``paddle_tpu_<subsystem>_<name>{labels}`` naming convention, with
  Prometheus-text and JSON exporters.
- :mod:`journal` — the **structured run journal**: a JSONL event
  stream with a run id and monotonic per-event sequence; span ids
  minted at submit/dispatch time correlate feeder fill, fused-dispatch
  chunks, serving worker execution, and async-PS pushes end to end.
- :mod:`recorder` — the **flight recorder**: the journal's bounded
  ring flushed to disk (atomic, CRC-manifested like checkpoints) on
  guard escalation, watchdog ``WorkerHung``, breaker trips, SIGTERM
  preemption, ``ReshardError``, and unhandled ``fit`` exceptions;
  rendered by ``tools/flight_dump.py``.
- :mod:`http` — the opt-in stdlib-only ``GET /metrics`` +
  ``GET /healthz`` endpoint both ``Trainer.serve_metrics()`` and
  ``PredictorServer.serve_metrics()`` expose.

See MIGRATION.md "Telemetry" for the metric name table, journal event
schema, and flight-recorder trigger/dump format.
"""

from .journal import (RunJournal, get_journal, new_run_id, parse_sample,
                      set_journal)
from .recorder import (FlightRecorder, default_flight_dir, flight_dump,
                       get_recorder)
from .registry import (Counter, FamiliesView, Gauge, Histogram, MetricFamily,
                       MetricsRegistry, counter_deltas, counter_family,
                       families_snapshot, gauge_family, get_registry,
                       histogram_family, merge_exports,
                       render_families_prometheus, validate_families)
from .http import TelemetryServer, serve_metrics

__all__ = [
    "Counter", "FamiliesView", "FlightRecorder", "Gauge", "Histogram",
    "MetricFamily", "MetricsRegistry", "RunJournal", "TelemetryServer",
    "counter_deltas", "counter_family", "default_flight_dir",
    "families_snapshot", "flight_dump", "gauge_family", "get_journal",
    "get_recorder", "get_registry", "histogram_family", "merge_exports",
    "new_run_id", "parse_sample", "render_families_prometheus",
    "serve_metrics", "set_journal", "validate_families",
]
