"""Summarize a jax.profiler trace captured by `bench.py --profile DIR`.

    python tools/trace_summary.py DIR [--top 25] [--lane SUBSTR]

Reads the newest */*.trace.json.gz under DIR (the perfetto-format trace
jax.profiler writes next to the xplane proto) with stdlib only — no
tensorboard plugin needed — and prints, per process lane, the ops
ranked by total duration. On a TPU capture the device lanes carry HLO
op names: the top rows of the busiest device lane ARE the "exact HLO
blocking it" answer the perf log asks for (DESIGN.md round-4 queue).
Python host frames ($-prefixed) are aggregated into one line so device
time is not drowned out.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os


def load_trace(dirname: str) -> dict:
    paths = sorted(glob.glob(os.path.join(dirname, "**", "*.trace.json.gz"),
                             recursive=True), key=os.path.getmtime)
    if not paths:
        raise SystemExit(f"no *.trace.json.gz under {dirname} — "
                         "run bench.py --profile first")
    with gzip.open(paths[-1]) as f:
        return json.load(f)


def summarize(trace: dict, top: int = 25, lane_filter: str | None = None):
    events = trace.get("traceEvents", [])
    # pid -> process name from metadata events
    pnames: dict = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pnames[e.get("pid")] = e.get("args", {}).get("name", "?")

    lanes: dict = collections.defaultdict(lambda: collections.Counter())
    totals: collections.Counter = collections.Counter()
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        lane = pnames.get(e.get("pid"), str(e.get("pid")))
        if lane_filter and lane_filter.lower() not in lane.lower():
            continue
        name = e.get("name", "?")
        if name.startswith("$"):  # python host frame: one bucket
            name = "[python host frames]"
        lanes[lane][name] += e["dur"]
        totals[lane] += e["dur"]

    for lane, _ in totals.most_common():
        ops = lanes[lane]
        print(f"\n=== lane: {lane} — {totals[lane] / 1e3:.1f} ms total "
              f"({len(ops)} distinct ops) ===")
        for name, d in ops.most_common(top):
            pct = 100.0 * d / max(totals[lane], 1)
            print(f"  {d / 1e3:10.2f} ms  {pct:5.1f}%  {name[:90]}")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("dir")
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--lane", default=None,
                   help="only lanes whose name contains this substring "
                        "(e.g. 'tpu' or 'device')")
    args = p.parse_args()
    summarize(load_trace(args.dir), top=args.top, lane_filter=args.lane)


if __name__ == "__main__":
    main()
