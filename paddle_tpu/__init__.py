"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up JAX/XLA/pallas re-design with the capabilities of the
reference framework (PaddlePaddle Fluid — see SURVEY.md): layer library,
optimizers with in-step regularization/clipping, functional state,
executor-style training, mesh-sharded data/tensor/sequence parallelism,
sparse embeddings, checkpointing, metrics, profiling, inference export.
"""

from . import clip, core, framework, initializer, layers, lr_scheduler
from . import optimizer, parallel, regularizer
from .core import CPUPlace, CUDAPlace, Place, TPUPlace, default_place
from .executor import Executor, Scope, Trainer
from .framework import (
    LayerHelper,
    ParamAttr,
    Program,
    build,
    create_parameter,
    create_variable,
    name_scope,
)
from .parallel import DistStrategy, ShardingRules, make_mesh

__version__ = "0.1.0"
