"""DataFeeder + device prefetch.

Analog of python/paddle/fluid/data_feeder.py (DataFeeder.feed:167 —
converts a list of per-sample tuples into batched dense arrays) and of
the py_reader/double_buffer device pipeline (operators/reader/
buffered_reader.cc, layers/io.py:478): ``DeviceFeeder`` runs the host
reader in a background thread and keeps N batches in flight on device so
host→HBM transfer overlaps with compute.

``DeviceFeeder(stack_k=K)`` additionally assembles K host batches into
one stacked super-batch ``{name: (K, batch, ...)}`` and transfers it in
ONE sharded put — the feed side of the fused multi-step dispatch
(``Trainer.run_steps`` / ``fit(steps_per_dispatch=K)``): one
host→device transfer and one launch per K optimizer steps instead of K.
"""

from __future__ import annotations

import queue as _queue
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..core.dtypes import convert_dtype


class DataFeeder:
    """Convert reader samples (tuples) into a named feed dict of batched
    numpy arrays (DataFeeder.feed analog, data_feeder.py:167)."""

    def __init__(self, feed_list: Sequence[str], dtypes: Optional[Sequence[Any]] = None):
        self.feed_list = list(feed_list)
        self.dtypes = list(dtypes) if dtypes is not None else [None] * len(self.feed_list)

    def feed(self, samples: Sequence[Tuple]) -> Dict[str, np.ndarray]:
        cols = list(zip(*samples))
        if len(cols) != len(self.feed_list):
            raise ValueError(
                f"sample arity {len(cols)} != feed_list arity {len(self.feed_list)}")
        out = {}
        for name, dt, col in zip(self.feed_list, self.dtypes, cols):
            arr = np.stack([np.asarray(v) for v in col])
            if dt is not None:
                arr = arr.astype(np.dtype(convert_dtype(dt).name))
            out[name] = arr
        return out


def stack_batches(bufs: Sequence[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    """Stack K same-shape feed dicts into one ``{name: (K, ...)}``
    super-batch (the fused-dispatch super-batch layout)."""
    return {k: np.stack([np.asarray(b[k]) for b in bufs]) for k in bufs[0]}


def _stackable(a: Dict[str, np.ndarray], b: Dict[str, np.ndarray]) -> bool:
    """Two batches can share a super-batch: same keys, shapes, dtypes
    (a short final reader batch must not poison the stack)."""
    if a.keys() != b.keys():
        return False
    for k in a:
        va, vb = np.asarray(a[k]), np.asarray(b[k])
        if va.shape != vb.shape or va.dtype != vb.dtype:
            return False
    return True


def _host_chunks(batches: Iterator[Dict[str, np.ndarray]], k: int):
    """The one chunking state machine both feed paths share: yields
    ``(n, host_feed)`` — full K-chunks stacked (``n == k``),
    remainder/odd-shape batches singly (``n == 1``, unstacked) so they
    fall through to the compiled single-step function with no
    fused-program retrace."""
    buf: List[Dict[str, np.ndarray]] = []
    for b in batches:
        if buf and not _stackable(buf[0], b):
            for s in buf:
                yield 1, s
            buf = []
        buf.append(b)
        if len(buf) == k:
            yield k, stack_batches(buf)
            buf = []
    for s in buf:
        yield 1, s


def iter_chunked(batches: Iterator[Dict[str, np.ndarray]], k: int,
                 put_fn: Callable, put_stacked_fn: Callable):
    """Synchronous chunker (the no-prefetch path of
    ``fit(steps_per_dispatch=K)``): ``_host_chunks`` plus the device
    put, yielding ``(n, device_feed)``."""
    for n, hb in _host_chunks(batches, k):
        yield n, (put_stacked_fn(hb) if n > 1 else put_fn(hb))


class DeviceFeeder:
    """Double-buffered host→device prefetch (py_reader + double_buffer
    analog). Wraps an iterator of feed dicts; ``__iter__`` yields dicts
    of on-device arrays while the next batches transfer in the
    background.

    With ``stack_k=K > 1`` the fill thread stacks K host batches into a
    super-batch, transfers it with ``put_stacked_fn`` in one put, and
    the iterator yields ``(n, feed)`` pairs — ``n == K`` for full
    chunks, ``n == 1`` (unstacked, via ``put_fn``) for remainder or
    shape-mismatched batches.

    The fill thread is CANCELLABLE: abandoning the iterator (break /
    exception / gc) or calling :meth:`close` unblocks it even when it is
    parked on a full queue holding device buffers — the old leak where a
    daemon thread pinned HBM until process exit.

    A reader/transfer exception on the fill thread PROPAGATES to the
    consumer: already-transferred batches drain first, then the original
    exception (fill-thread traceback attached) is re-raised at
    ``__next__`` — never a bare end-of-iteration that silently truncates
    the epoch. A fill thread that dies without delivering its END
    sentinel is detected by a liveness probe instead of hanging the
    consumer."""

    def __init__(self, batches: Callable[[], Iterator[Dict[str, np.ndarray]]],
                 put_fn: Optional[Callable[[Dict[str, np.ndarray]], Dict[str, jax.Array]]] = None,
                 capacity: int = 2, stack_k: int = 1,
                 put_stacked_fn: Optional[Callable] = None):
        self.batches = batches
        self.put_fn = put_fn or (lambda d: jax.device_put(d))
        self.put_stacked_fn = put_stacked_fn or self.put_fn
        self.capacity = capacity
        self.stack_k = max(1, int(stack_k))
        self._stops: List[threading.Event] = []
        self._threads: List[threading.Thread] = []

    def close(self):
        """Cancel every live fill thread (idempotent). Threads parked on
        a full queue wake on the stop flag and exit, dropping their
        device-buffer references."""
        for ev in self._stops:
            ev.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = [t for t in self._threads if t.is_alive()]

    def __iter__(self):
        q: _queue.Queue = _queue.Queue(maxsize=self.capacity)
        END = object()
        err: List[BaseException] = []
        stop = threading.Event()
        self._stops.append(stop)

        def put(item) -> bool:
            # bounded-wait put: a consumer that stopped consuming must
            # not strand this thread (and its device buffers) forever
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def fill():
            try:
                if self.stack_k > 1:
                    for n, hb in _host_chunks(self.batches(), self.stack_k):
                        if stop.is_set():
                            return
                        item = (n, self.put_stacked_fn(hb) if n > 1
                                else self.put_fn(hb))
                        if not put(item):
                            return
                else:
                    for b in self.batches():
                        if stop.is_set():
                            return
                        if not put(self.put_fn(b)):
                            return
            except BaseException as e:  # surfaced on the consumer side
                err.append(e)
            finally:
                if not put(END):
                    # stop was set (close() possibly from ANOTHER thread
                    # than the consumer): a consumer still parked in
                    # q.get() must not hang — if it is parked, the queue
                    # is empty and this delivery succeeds
                    try:
                        q.put_nowait(END)
                    except _queue.Full:
                        pass

        t = threading.Thread(target=fill, daemon=True)
        self._threads.append(t)
        t.start()
        try:
            while True:
                try:
                    item = q.get(timeout=0.5)
                except _queue.Empty:
                    # liveness check: a fill thread that died without
                    # managing to enqueue END (its sentinel put lost a
                    # race with close()) must not hang the consumer —
                    # and its reader error must still surface
                    if not t.is_alive():
                        # the thread may have enqueued its final batches
                        # (and END) between our timeout and this check —
                        # drain them before concluding, or the race
                        # silently truncates the epoch
                        while True:
                            try:
                                item = q.get_nowait()
                            except _queue.Empty:
                                break
                            if item is END:
                                break
                            yield item
                        if err:
                            raise err[0]
                        return
                    continue
                if item is END:
                    if err:
                        # re-raise the READER's exception at __next__
                        # with its original fill-thread traceback — a
                        # reader crash must abort the epoch loudly, not
                        # truncate it to a silent StopIteration
                        raise err[0]
                    return
                yield item
        finally:
            # break / exception / generator gc: release the fill thread
            stop.set()
